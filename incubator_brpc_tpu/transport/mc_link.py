"""Multi-controller device links — the device plane across PROCESSES.

The single-controller ``DeviceLink`` (transport/device_link.py) holds both
halves of the QP in one process: one drive fiber fills both parties' slots
and dispatches the exchange step. The reference transport this plane
re-thinks connects *separate hosts*: the RDMA handshake crosses the TCP
socket between two machines and each side runs its own send/recv rings
(/root/reference/src/brpc/rdma/rdma_endpoint.h:42-213, per-host device init
/root/reference/src/brpc/rdma/rdma_helper.cpp). This module is that
deployment for XLA's multi-controller model:

- **One process per party.** Each process owns ONE side of the link: its
  own device (``jax.local_devices()``), its own outbound queue, its own
  DeviceSocket and messenger. The peer's device is visible in
  ``jax.devices()`` through ``jax.distributed`` but not addressable.
- **The data plane is lockstep SPMD.** Both processes jit the SAME
  exchange step (``shard_map``/``ppermute`` over ``Mesh([dev_c, dev_s])``)
  and dispatch it the SAME number of times in the SAME order — the
  multi-controller contract. Each dispatch contributes only the local
  shard (``make_array_from_single_device_arrays`` with one row); XLA's
  collective moves both rows across ICI (gloo on the CPU test fabric).
- **The control plane rides the host socket.** Step *scheduling* — how
  many exchange steps both sides agree to dispatch — flows as tiny JSON
  messages on a full-duplex streaming-RPC channel (rpc/stream.py) opened
  by the same handshake RPC that proposes the link: the reference's
  rdmacm-over-TCP split (control on TCP, data on the device fabric),
  socket.cpp:1692-1704. Each side announces ``want`` = the step count its
  backlog needs; both sides run ``target = max(all wants)`` — a monotone
  join that needs no consensus round.
- **Credit: the collective IS the window.** The single-controller wire-ack
  mode gates dispatch on acks carried in received slot headers — the only
  signal an *independently dispatching* sender has. Under lockstep SPMD
  the same gate can deadlock: both sides can stall waiting for fresher
  acks that only future (never-dispatched) rows would carry. Here each
  side instead gates on its OWN undrained completions
  (``seq - delivered < window``): a receiver that stops draining stops
  dispatching, which stalls the peer's collectives at exactly ``window``
  steps of pipeline — backpressure propagates through the data plane
  itself, no ack round trip. The cumulative-delivered count still rides
  slot words 3+5 (the piggybacked imm-data ack,
  rdma_endpoint.h:176-195) as the cross-host drain telemetry: tests
  assert it advances, /status surfaces it, and a peer whose acks freeze
  while completions stall is failed by the wedge timer.
- **Shutdown is a two-message dance.** Either side freezes its wants and
  sends ``close_req(target)``; the peer freezes, computes
  ``final = max(targets)`` and answers ``close_ack(final)``. Stream
  ordering makes ``final`` identical on both sides (every want precedes
  its sender's close_req), so both dispatch exactly ``final`` steps and
  tear down — no half-joined collective.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from incubator_brpc_tpu.bvar import Adder
from incubator_brpc_tpu.transport.device_link import (
    HANDSHAKE_SERVICE,
    HANDSHAKE_METHOD,
    DeviceLink,
    DeviceSocket,
)
from incubator_brpc_tpu.utils.status import ErrorCode

logger = logging.getLogger(__name__)

mc_ctrl_msgs = Adder(name="mc_link_control_msgs")
# completion waits that made no progress (peer slow / not yet dispatching):
# each tick is one bounded 1 s retry before the wedge timer would fire
mc_stall_retries = Adder(name="mc_link_stall_retries")
mc_wedge_failures = Adder(name="mc_link_wedge_failures")


class MultiControllerLink(DeviceLink):
    """One side of a two-process device link (see module docstring).

    ``own_side``: 0 = client (the handshake proposer), 1 = server.
    ``control_send``: ships one small dict to the peer's ``on_control``
    (the streaming-RPC control plane). ``devices`` are the two GLOBAL
    devices in link order [client, server]; exactly ``devices[own_side]``
    must be addressable from this process.
    """

    def __init__(
        self,
        own_side: int,
        devices: List,
        slot_words: int = 16384,
        window: int = 8,
        control_send: Optional[Callable[[dict], None]] = None,
        wedge_timeout: float = 120.0,
    ):
        self.own_side = own_side
        self._control_send_fn = control_send
        self._target = 0  # steps both sides agreed to dispatch
        self._final_target: Optional[int] = None  # set by the close dance
        self._frozen = False  # close dance started: wants stop growing
        self._finished = False
        self._ctrl_close: Optional[Callable[[], None]] = None
        self.wedge_timeout = wedge_timeout
        super().__init__(
            devices,
            slot_words=slot_words,
            window=window,
            host_loopback=False,
            ack_mode="wire",
        )
        if self._step is None or self._mesh is None:
            raise ValueError(
                "multi-controller link needs two distinct global devices"
            )

    # -- control plane -------------------------------------------------------

    def _send_ctrl(self, msg: dict) -> None:
        fn = self._control_send_fn
        if fn is None:
            return
        try:
            fn(msg)
            mc_ctrl_msgs << 1
        except Exception:
            logger.exception("mc link control send failed")
            self.fail("control plane send failed")

    def on_control(self, msg: dict) -> None:
        """Peer control message (runs on the control stream's consumer
        fiber — ordered, one at a time)."""
        op = msg.get("op")
        if op == "want":
            with self._lock:
                if not self._frozen and not self._closed:
                    self._target = max(self._target, int(msg["n"]))
            self._kick()
        elif op == "close_req":
            with self._lock:
                self._frozen = True
                self._send_blocked = True  # refuse post-freeze queues
                # our own backlog queued before this freeze still needs
                # steps — fold it into the final count (a send() that
                # returned 0 must reach the wire; the peer learns the
                # raised final from the close_ack)
                need = (
                    self._out_nbytes[self.own_side] + self._slot_bytes - 1
                ) // self._slot_bytes
                if self._close_pending[self.own_side]:
                    need = max(need, 1)
                final = max(
                    self._target, int(msg["target"]), self._seq + need
                )
                self._target = final
                self._final_target = final
            self._send_ctrl({"op": "close_ack", "target": final})
            self._kick()
        elif op == "close_ack":
            with self._lock:
                final = int(msg["target"])
                self._target = max(self._target, final)
                self._final_target = final
            self._kick()
        else:
            logger.warning("mc link: unknown control op %r", op)

    def _propagate_want(self) -> None:
        """After queuing bytes: if the backlog needs steps beyond the
        current target, raise it locally and announce to the peer. The
        target only ever grows (monotone max both sides converge on)."""
        with self._lock:
            if self._closed or self._frozen:
                return
            need = (
                self._out_nbytes[self.own_side] + self._slot_bytes - 1
            ) // self._slot_bytes
            if self._close_pending[self.own_side]:
                need = max(need, 1)
            want = self._seq + need
            if want <= self._target:
                return
            self._target = want
        self._send_ctrl({"op": "want", "n": want})
        self._kick()

    # -- send / close --------------------------------------------------------

    def send(self, side: int, data, timeout: Optional[float] = 10.0) -> int:
        assert side == self.own_side, "mc link only sends from its own side"
        rc = super().send(side, data, timeout=timeout)
        if rc == 0:
            self._propagate_want()
        return rc

    def close(self, side: int) -> None:
        with self._lock:
            if self._closed or self._frozen:
                return
            self._close_pending[self.own_side] = True
            self._frozen = True
            self._send_blocked = True  # refuse post-freeze queues
            need = (
                self._out_nbytes[self.own_side] + self._slot_bytes - 1
            ) // self._slot_bytes
            self._target = max(self._target, self._seq + max(need, 1))
            t = self._target
        self._send_ctrl({"op": "close_req", "target": t})
        self._kick()

    # -- the lockstep drive loop --------------------------------------------

    def _make_local_slots(self, row: np.ndarray):
        import jax

        shard = jax.device_put(row[None, :], self.devices[self.own_side])
        return jax.make_array_from_single_device_arrays(
            (2, self._width), self._sharding, [shard]
        )

    def _drive(self) -> None:
        import time as _time

        from incubator_brpc_tpu.transport.device_link import link_steps

        stall_since: Optional[float] = None
        while True:
            with self._lock:
                if self._closed:
                    self._driving = False
                    return
                if (
                    self._final_target is not None
                    and self._seq >= self._final_target
                    and self._inflight == 0
                ):
                    self._driving = False
                    finish = True
                else:
                    finish = False
                    if self._seq >= self._target:
                        # nothing agreed to dispatch; on_control/send kick
                        # the drive again when the target grows
                        self._driving = False
                        return
                    if self._inflight >= self.window:
                        # own-delivery credit (see module docstring): wait
                        # for a completion; delivery releases the credit
                        need = self._cq.load() + 1
                    else:
                        need = None
                        row = self._fill_slot_locked(self.own_side)
                        seq = self._seq
                        self._seq += 1
                        self._inflight += 1
                        # feeds the step_rtt_us summary exactly like the
                        # base _drive: popped at in-order delivery
                        self._step_ts[seq] = _time.perf_counter()
            if finish:
                self._finish_close()
                return
            if need is not None:
                before = self._cq.load()
                self._cq.wait_for(need, timeout=1.0)
                if self._cq.load() == before:
                    # no completion progress: the peer may have stopped
                    # dispatching (died mid-burst). Gloo/XLA eventually
                    # error the half-joined collective; this timer bounds
                    # the wait even if the backend blocks silently.
                    mc_stall_retries << 1
                    now = _time.monotonic()
                    if stall_since is None:
                        stall_since = now
                    elif now - stall_since > self.wedge_timeout:
                        mc_wedge_failures << 1
                        self.fail(
                            "device plane wedged (peer not dispatching)"
                        )
                        with self._lock:
                            self._driving = False
                        return
                else:
                    stall_since = None
                continue
            stall_since = None
            try:
                out = self._step(self._make_local_slots(row))
            except Exception:
                logger.exception("mc link step dispatch failed")
                self.fail("link step dispatch failed")
                with self._lock:
                    self._driving = False
                return
            link_steps << 1
            self._cq.watch(
                out,
                on_complete=lambda arrays, error, _seq=seq: self._on_step_done(
                    _seq, arrays, error
                ),
            )

    def _finish_close(self) -> None:
        """Both sides dispatched exactly ``final_target`` steps and every
        delivery drained: the link is done. Quiet teardown — no fail()
        cascade into the peer (it finishes its own count)."""
        with self._lock:
            if self._finished or self._closed:
                return
            self._finished = True
            self._closed = True
        self._retire_metrics()  # clean close never reaches fail()
        sock = self.socks[self.own_side]
        if sock is not None:
            sock.set_failed(ErrorCode.ECLOSE, "device link closed")
        self._wbutex.add(1)
        self._wbutex.wake_all()
        self._close_ctrl()

    def fail(self, reason: str) -> None:
        super().fail(reason)
        # a dead link must not leave its control stream (and this link,
        # captured by the stream handler) attached to the shared TCP
        # connection forever
        self._close_ctrl()

    def _close_ctrl(self) -> None:
        fn, self._ctrl_close = self._ctrl_close, None
        if fn is not None:
            try:
                fn()
            except Exception:
                logger.exception("mc link control stream close raised")

    @property
    def peer_ack(self) -> int:
        """Cumulative frames the peer reported delivered (slot words 3+5) —
        the cross-host drain telemetry."""
        with self._lock:
            return self._peer_ack


# -- control stream plumbing ---------------------------------------------------


class _ControlHandler:
    """StreamHandler for the link's control plane. Messages are one JSON
    dict per stream message; they run on the stream's ordered consumer
    fiber, which is exactly the delivery order the close dance needs."""

    def __init__(self) -> None:
        self.link: Optional[MultiControllerLink] = None

    def on_received_messages(self, stream, messages: List[bytes]) -> None:
        link = self.link
        if link is None:
            return
        for m in messages:
            try:
                msg = json.loads(m.decode())
            except ValueError:
                logger.warning("mc link: undecodable control message")
                continue
            link.on_control(msg)

    def on_closed(self, stream) -> None:
        link = self.link
        if link is None:
            return
        # a clean shutdown closes the stream after the final step; only an
        # unexpected close (peer died) fails the link
        if link._final_target is None and not link._closed:
            link.fail("control stream closed by peer")

    def on_failed(self, stream, error_code: int, reason: str) -> None:
        link = self.link
        if link is not None and not link._closed:
            link.fail(f"control stream failed: {reason}")


def _stream_sender(stream) -> Callable[[dict], None]:
    def send(msg: dict) -> None:
        rc = stream.write(json.dumps(msg).encode(), timeout=10.0)
        if rc != 0:
            raise ConnectionError(f"control stream write failed: {rc}")

    return send


def _device_by_global_id(global_id: int):
    import jax

    for d in jax.devices():
        if d.id == global_id:
            return d
    raise ValueError(
        f"device id {global_id} not in this process's global view "
        f"(is jax.distributed initialized on both hosts?)"
    )


# -- establishment -------------------------------------------------------------


def accept_mc_handshake(server, cntl, req: dict) -> bytes:
    """Server half, called from the ``_tpu_transport.handshake`` handler
    when the proposal carries ``controller='multi'``. Accepts the control
    stream riding the same RPC, builds this process's link half over its
    own local device, and answers with the global device id so the client
    constructs the IDENTICAL mesh."""
    import jax

    from incubator_brpc_tpu.rpc.stream import StreamOptions, stream_accept

    handler = _ControlHandler()
    ctrl = stream_accept(cntl, StreamOptions(handler=handler))
    if ctrl is None:
        cntl.set_failed(
            ErrorCode.EREQUEST,
            "multi-controller handshake needs a control stream",
        )
        return b""
    try:
        client_dev = _device_by_global_id(int(req["client_device"]))
        slot_words = int(req.get("slot_words", 16384))
        window = int(req.get("window", 8))
    except (KeyError, ValueError, TypeError) as e:
        cntl.set_failed(ErrorCode.EREQUEST, f"bad mc handshake: {e}")
        return b""
    local = jax.local_devices()
    idx = server.options.device_index or 0
    server_dev = local[idx % len(local)]
    if server_dev.id == client_dev.id:
        cntl.set_failed(
            ErrorCode.EREQUEST,
            "client and server proposed the same device — a multi-"
            "controller link needs one device per process",
        )
        return b""
    link = MultiControllerLink(
        own_side=1,
        devices=[client_dev, server_dev],
        slot_words=slot_words,
        window=window,
        control_send=_stream_sender(ctrl),
    )
    link._ctrl_close = ctrl.close
    handler.link = link
    ds = DeviceSocket(
        link,
        side=1,
        messenger=server._messenger,
        context={"server": server},
    )
    # fingerprint consumption is symmetric: the client's advertised
    # device methods land on the server-side socket too, so EITHER end
    # can validate a (service, method) session proposal or a collective
    # lowering against what its peer actually registered
    ds.device_methods = dict(req.get("device_methods") or {})
    server._device_socks.append(ds)

    def _forget(sock, _server=server):
        try:
            _server._device_socks.remove(sock)
        except ValueError:
            pass
        sock.recycle()

    # fabriclint: allow(lifecycle-callback) self-pruning hook: drops the dead DeviceSocket from server._device_socks and recycles it — the server fails every device sock at stop, firing it
    ds.on_failed.append(_forget)
    return json.dumps(
        {
            "device": server_dev.id,
            "slot_words": slot_words,
            "window": window,
            "device_methods": {
                full: dm.fingerprint()
                for full, dm in getattr(server, "_device_methods", {}).items()
            },
        }
    ).encode()


def establish_mc_link(
    channel,
    device_index: int = 0,
    slot_words: int = 16384,
    window: int = 8,
    timeout_ms: float = 60000,
) -> DeviceSocket:
    """Client half: open the control stream, propose over the host socket
    (``device_index`` indexes this process's LOCAL devices), build side 0
    over the agreed global device pair. The returned DeviceSocket rides
    RPC frames over the lockstep SPMD exchange."""
    import jax

    from incubator_brpc_tpu.rpc import channel as channel_mod
    from incubator_brpc_tpu.rpc.controller import Controller
    from incubator_brpc_tpu.rpc.stream import StreamOptions, stream_create

    local = jax.local_devices()
    client_dev = local[device_index % len(local)]
    handler = _ControlHandler()
    ctrl = stream_create(StreamOptions(handler=handler))
    from incubator_brpc_tpu.rpc.device_method import registry_fingerprints

    payload = json.dumps(
        {
            "controller": "multi",
            "cookie": "",
            "client_device": client_dev.id,
            "slot_words": slot_words,
            "window": window,
            # symmetric advertisement (see accept_mc_handshake): the
            # collective method plane validates proposals against these
            "device_methods": registry_fingerprints(),
        }
    ).encode()
    cntl = Controller(timeout_ms=timeout_ms)
    cntl._force_host = True
    cntl = channel.call_method(
        HANDSHAKE_SERVICE,
        HANDSHAKE_METHOD,
        payload,
        cntl=cntl,
        request_stream=ctrl,
    )
    if cntl.failed():
        ctrl.close()
        raise ConnectionError(
            f"multi-controller handshake failed: {cntl.error_text}"
        )
    try:
        resp = json.loads(cntl.response_payload.decode())
        server_dev = _device_by_global_id(int(resp["device"]))
        link = MultiControllerLink(
            own_side=0,
            devices=[client_dev, server_dev],
            slot_words=int(resp.get("slot_words", slot_words)),
            window=int(resp.get("window", window)),
            control_send=_stream_sender(ctrl),
        )
    except Exception:
        # the server already built its half: closing the control stream
        # is what tells it to fail that half instead of wedging until
        # its wedge timer fires
        ctrl.close()
        raise
    link._ctrl_close = ctrl.close
    handler.link = link
    ds = DeviceSocket(link, side=0, messenger=channel_mod._client_messenger)
    ds.device_methods = resp.get("device_methods", {})
    return ds
