"""Acceptor — server-side connection intake (reference
src/brpc/acceptor.cpp:52-115,173-240): a oneshot IN handler on the listen
fd runs an accept-until-EAGAIN loop in a fiber, creating a Socket per
connection; stop() closes the listener and fails every accepted socket."""

from __future__ import annotations

import logging
import socket as _pysocket
import threading
from typing import Callable, Dict, Optional

from incubator_brpc_tpu.runtime.worker_pool import global_worker_pool
from incubator_brpc_tpu.transport.event_dispatcher import (
    EVENT_IN,
    global_dispatcher,
)
from incubator_brpc_tpu.transport.sock import Socket
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.status import ErrorCode

logger = logging.getLogger(__name__)


class Acceptor:
    def __init__(
        self,
        endpoint: EndPoint,
        messenger=None,
        user_message_handler: Optional[Callable] = None,
        on_connection: Optional[Callable[[Socket], None]] = None,
        conn_context: Optional[dict] = None,
        backlog: int = 128,
        inline_read: bool = False,
        ssl_context=None,
    ):
        self._messenger = messenger
        # server-side TLS: every accepted socket wraps with this context
        # and pumps its handshake from the reactor (ServerOptions.ssl)
        self._ssl_context = ssl_context
        self._user_message_handler = user_message_handler
        self._on_connection = on_connection
        self._inline_read = inline_read
        # seeded into every accepted Socket BEFORE it goes live (a request
        # can arrive in the same burst as the accept)
        self._conn_context = conn_context
        self._connections: Dict[int, Socket] = {}
        self._conn_lock = threading.Lock()
        self._accepting = False
        self._stopped = False
        self._paused = False  # lame-duck: listener closed, conns live

        self._unix_path: Optional[str] = None
        if endpoint.ip.startswith("unix://"):
            import os as _os

            path = endpoint.ip[len("unix://"):]
            if _os.path.exists(path):
                # only a DEAD socket file may be unlinked: hijacking a live
                # listener would silently black-hole its traffic (the TCP
                # branch gets this from EADDRINUSE)
                probe = _pysocket.socket(_pysocket.AF_UNIX, _pysocket.SOCK_STREAM)
                try:
                    probe.settimeout(0.2)
                    # a connect TIMEOUT is ambiguous (live listener with a
                    # full backlog) and must refuse, not unlink — only a
                    # clean refusal proves the file is stale
                    probe.connect(path)
                    probe.close()
                    raise OSError(f"unix socket {path} has a live listener")
                except (ConnectionRefusedError, FileNotFoundError):
                    probe.close()
                    try:
                        _os.unlink(path)
                    except OSError:
                        pass
            lsock = _pysocket.socket(_pysocket.AF_UNIX, _pysocket.SOCK_STREAM)
            try:
                lsock.bind(path)
            except OSError:
                lsock.close()  # a failed bind must not leak the listen fd
                raise
            self._unix_path = path
            resolved = endpoint
        else:
            lsock = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_STREAM)
            lsock.setsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_REUSEADDR, 1)
            try:
                lsock.bind((endpoint.ip, endpoint.port))
            except OSError:
                lsock.close()  # a failed bind must not leak the listen fd
                raise
            resolved = None  # filled after listen (ephemeral port)
        lsock.listen(backlog)
        lsock.setblocking(False)
        self._lsock = lsock
        self.endpoint = resolved or EndPoint(
            ip=endpoint.ip, port=lsock.getsockname()[1]
        )
        self._dispatcher = global_dispatcher(lsock.fileno())
        self._pool = global_worker_pool()
        self._dispatcher.add_consumer(lsock.fileno(), self._on_event, EVENT_IN)

    @property
    def port(self) -> int:
        return self.endpoint.port

    def connection_count(self) -> int:
        with self._conn_lock:
            return len(self._connections)

    def connections(self):
        with self._conn_lock:
            return list(self._connections.values())

    # -- intake -------------------------------------------------------------

    def _on_event(self, revents: int) -> None:
        with self._conn_lock:
            if self._accepting or self._stopped:
                return
            self._accepting = True
        self._pool.spawn(self._accept_loop)

    def _accept_loop(self) -> None:
        try:
            while not self._stopped:
                try:
                    conn, peer = self._lsock.accept()
                except BlockingIOError:
                    break
                except OSError:
                    return  # listener closed
                sock = Socket.from_accepted(
                    conn,
                    peer,
                    messenger=self._messenger,
                    user_message_handler=self._user_message_handler,
                    context=self._conn_context,
                    inline_read=self._inline_read,
                    ssl_context=self._ssl_context,
                    ssl_server_side=self._ssl_context is not None,
                )
                with self._conn_lock:
                    self._connections[sock.id] = sock
                # fabriclint: allow(lifecycle-callback) self-pruning map hook on a connection this acceptor owns and fails at stop — the hook dies with the socket it cleans up after
                sock.on_failed.append(self._forget)
                if self._on_connection is not None:
                    try:
                        self._on_connection(sock)
                    except Exception:
                        logger.exception("on_connection callback raised")
        finally:
            with self._conn_lock:
                self._accepting = False
            if not self._stopped and not self._paused:
                self._dispatcher.rearm(self._lsock.fileno(), EVENT_IN)

    def _forget(self, sock: Socket) -> None:
        with self._conn_lock:
            self._connections.pop(sock.id, None)

    # -- teardown -----------------------------------------------------------

    def pause(self) -> None:
        """Lame-duck: close the listener (new connects are refused by the
        kernel, so an LB redials elsewhere) while every accepted
        connection keeps being served. Irreversible; ``stop`` still
        performs the full teardown."""
        with self._conn_lock:
            if self._stopped or self._paused:
                return
            self._paused = True
        self._close_listener()

    def _close_listener(self) -> None:
        self._dispatcher.remove_consumer(self._lsock.fileno())
        if self._unix_path is not None:
            import os as _os

            # unlink BEFORE close: while we still own the listener, a
            # successor's liveness probe connects (live → refuses to bind),
            # so we can never delete a successor's fresh socket file
            try:
                _os.unlink(self._unix_path)
            except OSError:
                pass
        try:
            self._lsock.close()
        except OSError:
            pass

    def stop(self, close_connections: bool = True) -> None:
        was_paused = self._paused
        self._stopped = True
        if not was_paused:  # pause already tore the listener down
            self._close_listener()
        if close_connections:
            for sock in self.connections():
                sock.set_failed(ErrorCode.ECLOSE, "acceptor stopped")
