"""EventDispatcher — the epoll reactor (reference
src/brpc/event_dispatcher.cpp:275-343).

N dispatcher threads (flag ``event_dispatcher_num``) each own one epoll fd;
sockets are hashed onto dispatchers by fd (event_dispatcher.cpp:366-373).
Events are armed EPOLLONESHOT: when IN fires the dispatcher hands off to
the socket's handler (which schedules a fiber — the StartInputEvent
dedupe+bthread pattern, socket.cpp:2113-2158) and the fd stays disarmed
until the handler drains to EAGAIN and calls ``rearm``. That keeps the
reactor thread from spinning on a readable fd while a fiber is mid-read,
which is the same property the reference gets from edge-triggering.

Registration/modification from arbitrary threads goes through a command
queue drained by the dispatcher thread, kicked by a self-pipe.
"""

from __future__ import annotations

import logging
import os
import select
import threading
from typing import Callable, Dict, List, Optional, Tuple

from incubator_brpc_tpu.utils.flags import get_flag

logger = logging.getLogger(__name__)

EVENT_IN = select.EPOLLIN
EVENT_OUT = select.EPOLLOUT
EVENT_ERR = select.EPOLLERR | select.EPOLLHUP

_tls = threading.local()


def on_reactor_thread() -> bool:
    """True on an EventDispatcher loop thread. Work that may block for a
    long bound (connects, lock waits) checks this and defers to the worker
    pool instead of stalling a reactor's other sockets."""
    return getattr(_tls, "is_reactor", False)


class EventDispatcher:
    """One epoll loop thread. Handlers run inline and must be cheap
    (schedule a fiber / wake a butex and return)."""

    def __init__(self, name: str = "dispatcher"):
        self._epoll = select.epoll()
        self._handlers: Dict[int, Callable[[int], None]] = {}
        self._registered: Dict[int, int] = {}  # fd -> armed event mask
        self._lock = threading.Lock()
        self._cmds: List[Tuple] = []
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name=f"tbrpc-{name}", daemon=True
        )
        self._thread.start()

    # -- public API (any thread) -------------------------------------------

    def add_consumer(
        self, fd: int, handler: Callable[[int], None], events: int = EVENT_IN
    ) -> None:
        """Register ``handler(revents)`` for oneshot ``events`` on fd."""
        self._post(("add", fd, handler, events))

    def rearm(self, fd: int, events: int = EVENT_IN) -> None:
        """Re-enable oneshot events after the handler drained the fd."""
        self._post(("arm", fd, None, events))

    def remove_consumer(self, fd: int) -> None:
        self._post(("del", fd, None, 0))

    def stop_and_join(self) -> None:
        self._stopped = True
        self._kick()
        self._thread.join(timeout=5)
        try:
            self._epoll.close()
            os.close(self._wake_r)
            os.close(self._wake_w)
        except OSError:
            pass

    # -- internals ----------------------------------------------------------

    def _post(self, cmd: Tuple) -> None:
        with self._lock:
            self._cmds.append(cmd)
        self._kick()

    def _kick(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    def _apply_cmds(self) -> None:
        with self._lock:
            cmds, self._cmds = self._cmds, []
        for op, fd, handler, events in cmds:
            try:
                if op == "add":
                    self._handlers[fd] = handler
                    mask = events | EVENT_ERR | select.EPOLLONESHOT
                    if fd in self._registered:
                        self._epoll.modify(fd, mask)
                    else:
                        self._epoll.register(fd, mask)
                    self._registered[fd] = events
                elif op == "arm":
                    if fd in self._handlers:
                        self._epoll.modify(
                            fd, events | EVENT_ERR | select.EPOLLONESHOT
                        )
                        self._registered[fd] = events
                elif op == "del":
                    self._handlers.pop(fd, None)
                    if self._registered.pop(fd, None) is not None:
                        try:
                            self._epoll.unregister(fd)
                        except OSError:
                            pass
            except OSError as e:
                logger.debug("dispatcher cmd %s fd=%d failed: %s", op, fd, e)

    def _run(self) -> None:
        _tls.is_reactor = True
        wake_fd = self._wake_r
        self._epoll.register(wake_fd, select.EPOLLIN)
        while not self._stopped:
            self._apply_cmds()
            try:
                events = self._epoll.poll(1.0)
            except (OSError, ValueError):
                break
            for fd, revents in events:
                if fd == wake_fd:
                    try:
                        while os.read(wake_fd, 4096):
                            pass
                    except OSError:
                        pass
                    continue
                handler = self._handlers.get(fd)
                if handler is None:
                    continue
                try:
                    handler(revents)
                except Exception:  # noqa: BLE001 — a handler bug must not kill the reactor
                    logger.exception("event handler failed for fd %d", fd)


_dispatchers: List[EventDispatcher] = []
_dispatchers_lock = threading.Lock()


def global_dispatcher(fd: int = 0) -> EventDispatcher:
    """Dispatcher for this fd — hashed like the reference
    (event_dispatcher.cpp:366-373)."""
    global _dispatchers
    if not _dispatchers:
        with _dispatchers_lock:
            if not _dispatchers:
                n = max(1, int(get_flag("event_dispatcher_num")))
                _dispatchers = [
                    EventDispatcher(name=f"dispatcher-{i}") for i in range(n)
                ]
    return _dispatchers[fd % len(_dispatchers)]
