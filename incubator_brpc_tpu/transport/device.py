"""Device transport — the ``transport=tpu`` slot (reference analog:
src/brpc/rdma/rdma_endpoint.h:42-213 per-connection QP with send/recv
rings and credit-window flow control, block_pool.h registered-memory
blocks, rdma_completion_queue CQ delivery).

A ``DeviceEndpoint`` is the RdmaEndpoint re-thought for XLA:

- the "registered memory" is HBM itself: requests are framed into uint32
  device buffers (ops/framing), the *entire server hot path* — parse,
  verify, dispatch, handle, respond — is one fused XLA computation
  (models/tensor_echo), and only the response crosses back;
- the "credit window" bounds in-flight device dispatches
  (``window_size``, like _local_window_capacity rdma_endpoint.h:176-195):
  callers park on a butex when the window is full, completions release
  credits;
- the "completion queue" is a DeviceCompletionButex watcher
  (rdma_completion_queue delivering CQ events, here PJRT readiness);
- frames are bucketed to power-of-two payload sizes so XLA compiles one
  program per geometry and reuses it (static shapes; the block-pool
  fixed-block discipline applied to programs instead of buffers).

``DeviceEndpoint.call_bytes`` adapts the host byte world: payloads are
padded into the bucket and responses trimmed to the request's length
(handlers are shape-preserving word transforms). ``server_handler`` plugs
an endpoint into an ordinary Server method map, giving the full
host-RPC → HBM → fused-step → response path — the reference's
"flip transport=tpu and rerun the same example pair" moment (SURVEY §7
step 5).
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from incubator_brpc_tpu.bvar import Adder, LatencyRecorder
from incubator_brpc_tpu.ops import framing
from incubator_brpc_tpu.runtime.butex import Butex, ETIMEDOUT
from incubator_brpc_tpu.runtime.device_butex import DeviceCompletionButex
from incubator_brpc_tpu.utils.status import ErrorCode

MIN_BUCKET_WORDS = 64
MAX_BUCKET_WORDS = 1 << 24  # 64 MiB of uint32

device_calls = Adder(name="device_transport_calls")
device_latency = LatencyRecorder(name="device_transport_latency")


def _bucket_words(n: int) -> int:
    b = MIN_BUCKET_WORDS
    while b < n:
        b <<= 1
    if b > MAX_BUCKET_WORDS:
        raise ValueError(f"payload of {n} words exceeds max bucket")
    return b


class _PendingCall:
    __slots__ = ("ready", "response_words", "error_code", "error", "_t0")

    def __init__(self):
        self.ready = Butex(0)
        self.response_words = None
        self.error_code = 0
        self.error: Optional[BaseException] = None
        self._t0 = 0.0

    def settle(self) -> None:
        self.ready.add(1)
        self.ready.wake_all()

    def wait(self, timeout: Optional[float]) -> bool:
        while self.ready.load() == 0:
            if self.ready.wait(0, timeout=timeout) == ETIMEDOUT:
                return False
        return True


class DeviceEndpoint:
    """One device-resident service behind a credit window."""

    def __init__(
        self,
        service=None,
        device=None,
        window_size: int = 8,
        max_batch: int = 16,
    ):
        from collections import deque

        from incubator_brpc_tpu.models.tensor_echo import TensorEchoService

        self.service = service or TensorEchoService()
        self.device = device if device is not None else jax.devices()[0]
        self.window_size = window_size
        # Micro-batching: concurrent same-bucket calls stack into ONE
        # [B, width] dispatch of the vmapped step (batch sizes padded to
        # powers of two so jit compiles a handful of programs, not one
        # per B). This is the TPU-idiomatic fix for per-dispatch fixed
        # costs: 16 concurrent callers pay ~1-2 dispatches, not 16 — and
        # the stacked rows feed the MXU together. Clamped to the window:
        # at most window_size calls hold credits concurrently, so a
        # larger batch ceiling could never form.
        self.max_batch = max(1, min(max_batch, window_size))
        self._credits = Butex(window_size)
        self._cq = DeviceCompletionButex()
        self._queue = deque()  # (bucket, mid_u32, row, cid_u32, pending, n)
        self._qlock = threading.Lock()
        self._draining = False
        # frame-building fused INTO the jitted program; the batched form
        # vmaps the same fused step over stacked rows (jit's per-shape
        # cache gives one compiled program per (batch, bucket) geometry —
        # the fixed-block discipline)
        self._program = jax.jit(
            lambda padded, cid_lo, mid: self.service.step(
                framing.frame(
                    padded, (cid_lo, jnp.uint32(0)), method_id=mid
                )
            )
        )
        self._batch_program = jax.jit(
            jax.vmap(
                lambda padded, cid_lo, mid: self.service.step(
                    framing.frame(
                        padded, (cid_lo, jnp.uint32(0)), method_id=mid
                    )
                )
            )
        )

    # -- credit window (rdma_endpoint.h:176-195) ----------------------------

    def _acquire_credit(self, timeout: Optional[float]) -> bool:
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            c = self._credits.load()
            if c > 0 and self._credits.compare_exchange(c, c - 1):
                return True
            if c > 0:
                continue  # CAS race: retry
            remaining = None
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
            self._credits.wait(0, timeout=remaining)

    def _release_credit(self) -> None:
        self._credits.add(1)
        self._credits.wake(1)  # one credit frees one waiter, no herd

    @property
    def inflight(self) -> int:
        return self.window_size - self._credits.load()

    # -- call paths ---------------------------------------------------------

    def call_words(
        self,
        payload_words: np.ndarray,
        method_id: int = 0,
        correlation_id: int = 1,
        timeout: Optional[float] = 10.0,
    ) -> _PendingCall:
        """Async: frame → HBM → dispatch fused step → watch completion.
        Returns a _PendingCall the caller can wait on; the credit is held
        until the response settles (the per-WR ack discipline)."""
        pending = _PendingCall()
        if not self._acquire_credit(timeout):
            pending.error_code = ErrorCode.EOVERCROWDED
            pending.settle()
            return pending
        device_calls << 1
        pending._t0 = _time.monotonic()
        n = payload_words.shape[0]
        try:
            bucket = _bucket_words(max(1, n))
        except ValueError:
            # oversized payload: the credit MUST come back (a leak here
            # shrinks the window forever) and the caller gets the settled-
            # pending contract, not a raw exception
            self._release_credit()
            pending.error_code = ErrorCode.EREQUEST
            pending.settle()
            return pending
        padded = np.zeros(bucket, dtype=np.uint32)
        padded[:n] = payload_words
        with self._qlock:
            self._queue.append(
                (
                    bucket,
                    np.uint32(method_id),
                    padded,
                    np.uint32(correlation_id & 0xFFFFFFFF),
                    pending,
                    n,
                )
            )
            if self._draining:
                return pending  # the live drainer will pick it up
            self._draining = True
        # a DEDICATED thread, not a worker-pool fiber: handler fibers
        # block waiting on these dispatches, so a saturated pool could
        # strand the drainer behind the very callers it must unblock
        threading.Thread(
            target=self._drain, name="tbrpc-dev-batch", daemon=True
        ).start()
        return pending

    # -- the batching drainer (single-drainer, like the link's _kick) -------

    def _drain(self) -> None:
        while True:
            with self._qlock:
                if not self._queue:
                    self._draining = False
                    return
                # group the head run of SAME-BUCKET entries (shape =
                # program identity); mids/cids are per-row arguments
                bucket = self._queue[0][0]
                batch = []
                while (
                    self._queue
                    and self._queue[0][0] == bucket
                    and len(batch) < self.max_batch
                ):
                    batch.append(self._queue.popleft())
                more = bool(self._queue)
            if more:
                # staggered arrivals: submit THIS batch on its own thread
                # so the next batch's (tunnel-expensive) host→device
                # submission overlaps it — a single submitting thread
                # would serialize exactly the fixed costs the window
                # exists to overlap (dedicated threads for the same
                # reason as _drain itself)
                threading.Thread(
                    target=self._dispatch_batch,
                    args=(bucket, batch),
                    name="tbrpc-dev-batch-tx",
                    daemon=True,
                ).start()
            else:
                self._dispatch_batch(bucket, batch)

    def _dispatch_batch(self, bucket: int, batch: list) -> None:
        b = len(batch)
        # pad the batch to a power of two so jit compiles O(log max_batch)
        # programs per bucket; pad rows are zero frames whose (flagged-
        # garbage) response rows are simply ignored
        bpad = 1
        while bpad < b:
            bpad <<= 1
        rows = np.zeros((bpad, bucket + 0), dtype=np.uint32)
        cids = np.zeros(bpad, dtype=np.uint32)
        mids = np.zeros(bpad, dtype=np.uint32)
        for i, (_, mid, padded, cid, _p, _n) in enumerate(batch):
            rows[i] = padded
            cids[i] = cid
            mids[i] = mid
        try:
            if bpad == 1:
                response = self._program(  # single call: no vmap overhead
                    jax.device_put(jnp.asarray(rows[0]), self.device),
                    jnp.uint32(int(cids[0])),
                    jnp.uint32(int(mids[0])),
                )
            else:
                response = self._batch_program(
                    jax.device_put(jnp.asarray(rows), self.device),
                    jnp.asarray(cids),
                    jnp.asarray(mids),
                )
        except Exception as e:  # dispatch failed: settle the whole batch
            for _, _mid, _padded, _cid, pending, _n in batch:
                self._release_credit()
                pending.error = e
                pending.error_code = ErrorCode.EINTERNAL
                pending.settle()
            return

        def on_complete(arrays, error, _batch=batch, _single=(bpad == 1)):
            try:
                host = None
                if error is None:
                    host = np.asarray(jax.device_get(arrays))
            except Exception as e:  # noqa: BLE001 — fetch failed
                error, host = e, None
            for i, (_, _mid, _padded, _cid, pending, n) in enumerate(_batch):
                try:
                    if error is not None:
                        pending.error = error
                        pending.error_code = ErrorCode.EINTERNAL
                    else:
                        row = host if _single else host[i]
                        _, words, err = _parse_response(row)
                        pending.error_code = int(err)
                        pending.response_words = words[:n]
                    device_latency << (
                        _time.monotonic() - pending._t0
                    ) * 1e6
                except Exception as e:  # noqa: BLE001 — parse failed
                    pending.error = e
                    pending.error_code = ErrorCode.EINTERNAL
                    pending.response_words = None
                finally:
                    self._release_credit()
                    pending.settle()

        self._cq.watch(response, on_complete=on_complete)

    def call_bytes(
        self,
        payload: bytes,
        method_id: int = 0,
        correlation_id: int = 1,
        timeout: Optional[float] = 10.0,
    ) -> Tuple[int, bytes]:
        """Sync byte adapter: pad to words, run, trim the response to the
        request's byte length (handlers are shape-preserving)."""
        nbytes = len(payload)
        pad = (-nbytes) % 4
        words = np.frombuffer(payload + b"\x00" * pad, dtype=np.uint32)
        # ONE deadline budget across credit-wait + completion-wait
        deadline = None if timeout is None else _time.monotonic() + timeout
        pending = self.call_words(
            words, method_id=method_id, correlation_id=correlation_id,
            timeout=timeout,
        )
        remaining = None
        if deadline is not None:
            remaining = max(0.0, deadline - _time.monotonic())
        if not pending.wait(remaining):
            return ErrorCode.ERPCTIMEDOUT, b""
        if pending.error_code:
            return pending.error_code, b""
        return 0, pending.response_words.tobytes()[:nbytes]

    def warm(self, payload_bytes: int, timeout: float = 300.0) -> None:
        """Compile every (batch, bucket) geometry this payload size can hit
        — single + each power-of-two batch up to max_batch — so a timed or
        latency-sensitive workload never pays XLA compilation mid-flight.
        Batch formation depends on arrival timing, so a concurrency burst
        does NOT reliably warm the larger geometries; this does."""
        n_words = max(1, (payload_bytes + 3) // 4)
        bucket = _bucket_words(n_words)
        row = np.zeros(bucket, dtype=np.uint32)
        outs = [
            self._program(
                jax.device_put(jnp.asarray(row), self.device),
                jnp.uint32(1),
                jnp.uint32(0),
            )
        ]
        b = 2
        while b <= self.max_batch:
            rows = np.zeros((b, bucket), dtype=np.uint32)
            outs.append(
                self._batch_program(
                    jax.device_put(jnp.asarray(rows), self.device),
                    jnp.zeros(b, dtype=jnp.uint32),
                    jnp.zeros(b, dtype=jnp.uint32),
                )
            )
            b <<= 1
        jax.block_until_ready(outs)

    # -- host-plane integration --------------------------------------------

    def server_handler(self, method_id: int = 0, timeout: float = 60.0):
        """An ordinary Server handler that delegates to this endpoint: the
        request payload goes to HBM, the fused step runs, the response
        comes back — RPC in, device compute, RPC out. ``timeout`` budgets
        credit-wait + queued-batch dispatch + completion (under bursts a
        call may ride the second or third micro-batch)."""

        def handler(cntl, request: bytes) -> bytes:
            code, out = self.call_bytes(
                request,
                method_id=method_id,
                correlation_id=cntl.call_id or 1,
                timeout=timeout,
            )
            if code:
                cntl.set_failed(code, f"device call failed ({code})")
                return b""
            return out

        return handler


def _parse_response(host_frame: np.ndarray):
    """Host-side parse of a device response frame (the 8-word header layout
    of ops/framing.py, read with numpy — no second device round-trip).
    Word 7 is the error code on responses."""
    header = host_frame[: framing.HEADER_WORDS]
    payload = host_frame[framing.HEADER_WORDS :]
    return header, payload, header[7]
