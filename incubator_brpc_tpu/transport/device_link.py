"""Two-party device transport — the ``transport=tpu`` data plane.

The reference template is the RDMA endpoint pair (rdma/rdma_endpoint.h:
42-213): two Sockets handshake over their TCP connection ("RDMA" magic +
cookie, socket.cpp:1692-1704), then move the SAME wire frames through
queue-pair send/recv rings in registered memory with a credit window
(rdma_endpoint.h:105-123,176-195), completions feeding the normal input
path (rdma_completion_queue.cpp:152). This module is that design
re-thought for XLA devices:

- **The QP is a 2-device mesh axis.** A connection binds one device per
  party; the data primitive is one jitted *link step* that exchanges both
  parties' outbound slots in a single ``shard_map``/``ppermute`` over
  ``Mesh([dev_a, dev_b], ("link",))`` — a full-duplex DMA across ICI (on
  the test CPU mesh, across virtual devices; with both parties on one
  chip, the exchange degenerates to an on-device row swap). One dispatch
  moves both directions; in a multi-controller deployment the same jitted
  step is dispatched SPMD by each host, which is exactly how the design
  scales off one process.
- **Slots are the rings.** Each step carries one fixed-geometry uint32
  slot per direction (negotiated ``slot_words``); the link is a BYTE
  STREAM: queued host frames (tbus_std bytes — the same frames TCP
  carries, as RDMA carries baidu_std bytes) are packed head-to-tail into
  slots and re-cut by the receiver's normal InputMessenger loop. XLA's
  functional model replaces ring *reuse* with fresh step outputs, so the
  credit window bounds un-drained in-flight steps instead of ring slots.
- **Handshake rides the host socket.** The client sends a cookie +
  device/geometry proposal as an ordinary RPC on the already-connected
  TCP socket (the reference's magic+cookie over TCP); the server builds
  its half and answers with its device. Control stays on TCP, data moves
  on the device plane — the RDMA split exactly.
- **Completions are DeviceCompletionButex events.** Step outputs are
  watched; a per-link reorder buffer delivers them in sequence into each
  side's ``DeviceSocket`` read buffer and messenger (the CQ feeding
  InputMessenger, rdma_completion_queue.cpp:152).
- **Flow control**: writers park on a butex once the outbound backlog
  passes the window's byte budget (EOVERCROWDED past a hard cap); slot
  headers carry cumulative seq/ack words like the RDMA endpoint's
  piggybacked imm-data acks (rdma_endpoint.h:176-195).
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from incubator_brpc_tpu.bvar import Adder, LatencyRecorder, PerSecond
from incubator_brpc_tpu.runtime.butex import Butex, ETIMEDOUT
from incubator_brpc_tpu.runtime.device_butex import DeviceCompletionButex
from incubator_brpc_tpu.runtime.worker_pool import global_worker_pool
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.status import ErrorCode

logger = logging.getLogger(__name__)

LINK_MAGIC = 0x5450554C  # "TPUL"
LINK_HEADER_WORDS = 8
# header words: 0 magic, 1 used_bytes, 2 seq, 3 ack_lo, 4 flags,
# 5 ack_hi (the delivered count is 64-bit on the wire: a wrapped 32-bit
# ack would wedge the wire-mode credit window), 6-7 reserved
F_DATA = 1
F_CLOSE = 2

HANDSHAKE_SERVICE = "_tpu_transport"
HANDSHAKE_METHOD = "handshake"

link_steps = Adder(name="device_link_steps")
link_bytes = Adder(name="device_link_bytes")
link_acks = Adder(name="device_link_ack_steps")  # wire-mode catch-up steps
link_errors = Adder(name="device_link_errors")  # fail() calls, all links
# send() attempts refused with EOVERCROWDED after a full window-stall wait
link_overcrowded = Adder(name="device_link_overcrowded")

_link_ids = itertools.count(1)  # per-link bvar namespace: device_link_<n>_*

# Every live link, for the interpreter-exit quiesce: a teardown-triggered
# close frame dispatches one final exchange step on a worker fiber; if the
# process exits while that fiber is inside the XLA dispatch (or the CQ
# watcher inside the PJRT wait), CPython finalizes under it and the C++
# teardown aborts ("terminate called ... FATAL: exception not rethrown").
# The atexit hook outwaits in-flight drives/steps (bounded), then drains
# the completion watchers.
import weakref

_all_links: "weakref.WeakSet" = weakref.WeakSet()
_links_lock = threading.Lock()


def _quiesce_links(timeout: float = 10.0) -> None:
    import time as _time

    deadline = _time.monotonic() + timeout
    with _links_lock:
        links = list(_all_links)
    for link in links:
        while _time.monotonic() < deadline:
            with link._lock:
                idle = not link._driving and link._inflight == 0
            if idle:
                break
            _time.sleep(0.01)
    # the drives above may have submitted completion watches: drain them
    from incubator_brpc_tpu.runtime import device_butex as _db

    if _db._watchers is not None:
        _db._watchers.quiesce(timeout=max(0.1, deadline - _time.monotonic()))


import atexit

atexit.register(_quiesce_links)


class DeviceLink:
    """One established two-party link: the QP pair + CQ + window."""

    def __init__(
        self,
        devices: List,
        slot_words: int = 16384,
        window: int = 8,
        host_loopback: Optional[bool] = None,
        ack_mode: str = "local",
    ):
        """``host_loopback``: when both parties share ONE device the
        exchange is a pure swap — the peer's bytes are already on this
        host (they were queued here) and the consumer is this host's
        messenger, so a device round trip would be two tunnel crossings
        that move no information. Default (None) takes the fast path for
        the shared-device geometry; tests pass False to force the jitted
        on-device swap.

        ``ack_mode``: how the credit window learns about drained steps.
        'local' (default) gates on this process's shared delivery counter
        — correct and cheapest when both parties live in one controller.
        'wire' gates on the CUMULATIVE-DELIVERED count carried in received
        slot headers (word 3) — the information flow a multi-controller
        deployment has, where each host only observes its own deliveries:
        the RDMA endpoint's piggybacked imm-data acks, with ack-only steps
        dispatched when acks lag half the window (the accumulated-ack +
        SendImm scheme, rdma_endpoint.h:117-123,176-195)."""
        if slot_words < 64:
            raise ValueError("slot_words too small")
        if ack_mode not in ("local", "wire"):
            raise ValueError(f"unknown ack_mode {ack_mode!r}")
        self.devices = devices  # [dev_side0, dev_side1]
        self.slot_words = slot_words
        self.window = window
        self.ack_mode = ack_mode
        self._peer_ack = 0  # wire mode: max delivered-count seen in rows
        self._acks_sent = 0  # wire mode: highest ack value put on the wire
        self._host_loopback = host_loopback
        self._slot_bytes = slot_words * 4
        self._lock = threading.Lock()
        self._out: List[deque] = [deque(), deque()]  # pending bytes per side
        self._out_nbytes = [0, 0]
        self._close_pending = [False, False]
        self._closed = False
        # admission gate mc_link flips when its close dance freezes the
        # step budget: bytes queued after the freeze could never be
        # dispatched, so they must be REFUSED, not silently dropped —
        # checked in the same critical section that admits the queue
        # extension (always False for the in-process link)
        self._send_blocked = False
        self._seq = 0  # steps dispatched
        self._next_deliver = 0  # next seq to hand to the sockets
        self._inflight = 0  # dispatched, not yet drained
        self._reorder: Dict[int, tuple] = {}
        self._deliver_lock = threading.Lock()  # one in-order deliverer
        self._deliver_tid: Optional[int] = None  # thread inside _deliver
        self._driving = False
        self._wbutex = Butex(0)  # writers park here on backlog
        self._cq = DeviceCompletionButex()
        self.socks: List[Optional["DeviceSocket"]] = [None, None]
        self._pool = global_worker_pool()
        # -- per-link instrumentation (scraped at /brpc_metrics): the
        # observable face of bench.py's link_stream_gbps — rtt per exchange
        # step (dispatch -> in-order delivery), flush = the staging gather
        # into a slot, pump = feeding delivered bytes into the messenger,
        # plus bytes-per-second windows each way. Retired (hidden from the
        # registry) when the link dies so churning links don't accumulate.
        self.link_id = next(_link_ids)
        pfx = f"device_link_{self.link_id}"
        self._m_out_bytes = Adder()
        self._m_in_bytes = Adder()
        self._m_rtt = LatencyRecorder(name=f"{pfx}_step_rtt_us")
        self._m_flush = LatencyRecorder(name=f"{pfx}_flush_us")
        self._m_pump = LatencyRecorder(name=f"{pfx}_pump_us")
        self._m_out_rate = PerSecond(self._m_out_bytes, name=f"{pfx}_out_bytes_second")
        self._m_in_rate = PerSecond(self._m_in_bytes, name=f"{pfx}_in_bytes_second")
        self._metrics_retired = False
        self._step_ts: Dict[int, float] = {}  # seq -> dispatch perf_counter
        self._build_step()
        with _links_lock:
            _all_links.add(self)

    def _retire_metrics(self) -> None:
        """Drop this link's names from the expose registry (terminal).
        The aggregate device_link_* counters live on."""
        if self._metrics_retired:
            return
        self._metrics_retired = True
        for v in (
            self._m_rtt, self._m_flush, self._m_pump,
            self._m_out_rate, self._m_in_rate,
        ):
            try:
                v.hide()
            except Exception:
                pass

    def _maybe_retire_metrics(self) -> None:
        """Clean-close path: the base link never reaches fail() on an
        orderly ECLOSE dance, so once every handshaken side's socket has
        left CONNECTED the link carries no more traffic — drop its names
        then too (churning links must not accumulate registry entries)."""
        from incubator_brpc_tpu.transport.sock import CONNECTED

        socks = [s for s in self.socks if s is not None]
        if socks and all(s.state != CONNECTED for s in socks):
            self._retire_metrics()

    # -- the ICI primitive ---------------------------------------------------

    def _build_step(self) -> None:
        import jax
        import jax.numpy as jnp

        width = LINK_HEADER_WORDS + self.slot_words
        self._width = width
        same_device = (
            len({getattr(d, "id", i) for i, d in enumerate(self.devices)}) == 1
        )
        if self._host_loopback is None:
            self._host_loopback = same_device
        if self._host_loopback:
            # shared-device geometry: pure host swap — no dispatch, no
            # readback (the on-chip fast path; VERDICT r3 item 1). All the
            # link machinery above the step (slot packing, seq/ack headers,
            # credit window, in-order delivery) still runs.
            self._mesh = None
            self._sharding = None
            self._step = None
            return
        if same_device:
            # forced device loop on one chip (tests exercising dispatch)
            self._mesh = None
            self._sharding = None
            self._step = jax.jit(lambda slots: slots[::-1])
            return
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        try:
            from jax import shard_map  # JAX >= 0.8
        except ImportError:  # pragma: no cover — older JAX
            from jax.experimental.shard_map import shard_map

        mesh = Mesh(np.asarray(self.devices), ("link",))
        self._mesh = mesh
        self._sharding = NamedSharding(mesh, P("link"))

        def exchange(slots):
            return shard_map(
                lambda x: jax.lax.ppermute(x, "link", [(0, 1), (1, 0)]),
                mesh=mesh,
                in_specs=P("link"),
                out_specs=P("link"),
            )(slots)

        self._step = jax.jit(exchange, out_shardings=self._sharding)

    def _make_slots(self, rows: List[np.ndarray]):
        """Device-place both parties' outbound slots as one array sharded
        over the link axis (each row lives on its party's device)."""
        import jax
        import jax.numpy as jnp

        if self._mesh is None:
            return jax.device_put(
                jnp.asarray(np.stack(rows)), self.devices[0]
            )
        shards = [
            jax.device_put(rows[i][None, :], self.devices[i]) for i in (0, 1)
        ]
        return jax.make_array_from_single_device_arrays(
            (2, self._width), self._sharding, shards
        )

    # -- send side -----------------------------------------------------------

    def attach(self, side: int, sock: "DeviceSocket") -> None:
        self.socks[side] = sock

    def send(self, side: int, data, timeout: Optional[float] = 10.0) -> int:
        """Queue bytes (bytes or IOBuf) for the peer. 0, or EOVERCROWDED
        when the backlog stays above the window's byte budget past
        ``timeout``. The in-order deliverer thread never parks here (a
        handler responding inline during delivery would deadlock the link
        waiting on itself) — its writes are admitted past the budget,
        bounded by one response per delivered request.

        An IOBuf is queued as zero-copy views of its blocks (kept alive by
        the IOBuf itself): the only host copy of outbound payload bytes is
        the gather into the slot — the registered-ring staging write of the
        RDMA template (rdma_endpoint.h:105-123)."""
        if self._closed:
            return ErrorCode.EFAILEDSOCKET
        if isinstance(data, (bytes, bytearray, memoryview)):
            chunks = [[memoryview(data).cast("B"), data]]
        else:  # IOBuf: views stay valid while the IOBuf is referenced
            chunks = [[v, data] for v in data.views() if len(v)]
        n = sum(len(v) for v, _ in chunks)
        if n == 0:
            return 0
        budget = self.window * self._slot_bytes
        deadline = None
        while True:
            with self._lock:
                if self._closed or self._send_blocked:
                    return ErrorCode.EFAILEDSOCKET
                if (
                    self._out_nbytes[side] <= budget
                    or threading.get_ident() == self._deliver_tid
                ):
                    self._out[side].extend(chunks)
                    self._out_nbytes[side] += n
                    break
                seq = self._wbutex.load()
            # window stall: park until a step drains (credit released)
            import time as _time

            if deadline is None:
                deadline = _time.monotonic() + (timeout if timeout else 10.0)
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                link_overcrowded << 1
                return ErrorCode.EOVERCROWDED
            self._wbutex.wait(seq, timeout=remaining)
        self._kick()
        return 0

    def close(self, side: int) -> None:
        with self._lock:
            if self._closed:
                return
            self._close_pending[side] = True
        self._kick()

    def _kick(self) -> None:
        with self._lock:
            if self._driving or self._closed:
                return
            self._driving = True
        self._pool.spawn(self._drive)

    # -- the drainer (single-drainer discipline, like Socket's KeepWrite) ----

    def _has_work(self) -> bool:
        return bool(
            self._out[0] or self._out[1]
            or self._close_pending[0] or self._close_pending[1]
        )

    def _window_full_locked(self) -> bool:
        """Credit check under the link lock. 'local': dispatched-but-
        undrained steps (this process sees both deliveries). 'wire': how
        far our seq runs ahead of the peer's CUMULATIVE-DELIVERED count as
        carried in received slot word 3 — the only signal a
        multi-controller host has (rdma_endpoint.h:176-195)."""
        if self.ack_mode == "wire":
            return self._seq - self._peer_ack >= self.window
        return self._inflight >= self.window

    def _drive(self) -> None:
        while True:
            ack_only = False
            with self._lock:
                if self._closed or not self._has_work():
                    self._driving = False
                    return
                if self._window_full_locked():
                    # wire mode: when the acks we have put on the wire lag
                    # our deliveries by nearly a full window, the peer may
                    # be blocked on US — dispatch ONE over-window catch-up
                    # step carrying the fresh cumulative ack (and any
                    # queued data; a pure ack frame would starve data at
                    # window=1). The accumulated-ack + SendImm scheme,
                    # rdma_endpoint.h:117-123,176-195. Threshold window-1
                    # (was window/2, VERDICT r5 item 8): acks are
                    # cumulative, so ONE catch-up step flushes the whole
                    # backlog — batching to the window edge halves the
                    # over-window steps the link pays per byte while
                    # deliveries (which cap the lag at `window`) still
                    # guarantee the threshold is reachable, so the
                    # two-sided stall cannot wedge.
                    if (
                        self.ack_mode == "wire"
                        and self._next_deliver - self._acks_sent
                        >= max(1, self.window - 1)
                    ):
                        ack_only = True
                        need = None
                    else:
                        # local mode waits for a completion; wire mode
                        # waits for DELIVERY progress (deliveries advance
                        # _peer_ack, and _wbutex bumps on each one)
                        need = (
                            self._wbutex.load()
                            if self.ack_mode == "wire"
                            else self._cq.load() + 1
                        )
                else:
                    need = None
                if need is None:
                    rows = [self._fill_slot_locked(s) for s in (0, 1)]
                    seq = self._seq
                    self._seq += 1
                    self._inflight += 1
                    self._step_ts[seq] = time.perf_counter()
            if need is not None:
                if self.ack_mode == "wire":
                    self._wbutex.wait(need, timeout=1.0)
                else:
                    self._cq.wait_for(need, timeout=1.0)
                continue
            if ack_only:
                link_acks << 1
            if self._step is None:
                # host-loopback fast path: the swap IS the exchange —
                # deliver side i the peer's outbound row, no device hop.
                # Guarded like the dispatch path: a raising handler during
                # the synchronous delivery must fail the link, not strand
                # _driving=True with the queue wedged.
                link_steps << 1
                try:
                    self._on_step_done(seq, ("host", [rows[1], rows[0]]), None)
                except Exception:
                    logger.exception("loopback link delivery failed")
                    self.fail("loopback delivery failed")
                    with self._lock:
                        self._driving = False
                    return
                continue
            try:
                out = self._step(self._make_slots(rows))
            except Exception:
                logger.exception("device link step dispatch failed")
                self.fail("link step dispatch failed")
                with self._lock:
                    self._driving = False
                return
            link_steps << 1
            self._cq.watch(
                out,
                on_complete=lambda arrays, error, _seq=seq: self._on_step_done(
                    _seq, arrays, error
                ),
            )

    def _fill_slot_locked(self, side: int) -> np.ndarray:
        """Pack queued views head-to-tail into one slot (byte stream: a
        frame may split across slots; the receiver's messenger re-cuts).
        ONE gather copy per byte — the staging write into the 'ring'.
        np.empty, not np.zeros: the receiver only reads ``used`` bytes,
        so a full-slot memset per step would touch every byte twice
        (VERDICT r3 weak #5); only the header words are written below."""
        t0 = time.perf_counter()
        row = np.empty(self._width, dtype=np.uint32)
        rb = row.view(np.uint8)
        used = 0
        q = self._out[side]
        cap = self._slot_bytes
        base = LINK_HEADER_WORDS * 4
        while q and used < cap:
            entry = q[0]
            view = entry[0]
            take = min(len(view), cap - used)
            rb[base + used : base + used + take] = np.frombuffer(
                view[:take], dtype=np.uint8
            )
            if take == len(view):
                q.popleft()  # keepalive dropped with the entry
            else:
                entry[0] = view[take:]
            used += take
        self._out_nbytes[side] -= used
        if self._step is not None and used < cap:
            # the whole row crosses the wire on the device path: an
            # uninitialized tail would ship this process's freed heap to
            # the peer (free in the full-slot steady state)
            rb[base + used :] = 0
        flags = F_DATA if used else 0
        if not q and self._close_pending[side]:
            flags |= F_CLOSE
            self._close_pending[side] = False
        row[0] = LINK_MAGIC
        row[1] = used
        row[2] = self._seq & 0xFFFFFFFF
        row[5:LINK_HEADER_WORDS] = 0  # reserved words must not leak heap
        row[5] = (self._next_deliver >> 32) & 0xFFFFFFFF  # ack high word
        self._acks_sent = self._next_deliver  # words 3+5 carry this
        # words 3(+5) carry the cumulative delivered count on the wire
        # (the RDMA endpoint's piggybacked imm-data ack slot). ack_mode=
        # 'local' gates the window on the shared in-process counter and
        # only WRITES these; ack_mode='wire' — the multi-controller flow —
        # gates on the values READ from received rows (_deliver).
        row[3] = self._next_deliver & 0xFFFFFFFF
        row[4] = flags
        if used:
            link_bytes << used
            self._m_out_bytes << used
        self._m_flush << (time.perf_counter() - t0) * 1e6
        return row

    # -- receive side --------------------------------------------------------

    def _on_step_done(self, seq: int, arrays, error) -> None:
        if error is not None:
            logger.error("device link step failed: %s", error)
            self.fail(f"link step failed: {error}")
            return
        with self._lock:
            self._reorder[seq] = arrays
        self._drain_ready()
        self._kick()

    def _drain_ready(self) -> None:
        """Deliver completed steps strictly in sequence. CQ watcher threads
        complete out of order; _deliver_lock admits ONE deliverer at a time
        and the pop of _next_deliver happens under the link lock, so the
        byte stream can never interleave (a mis-ordered chunk would corrupt
        every frame after it). The window credit (inflight) is released
        only after delivery — un-drained outputs are the occupied ring."""
        while True:
            with self._deliver_lock:
                with self._lock:
                    arrays = self._reorder.pop(self._next_deliver, None)
                    if arrays is None:
                        return
                    dispatched_at = self._step_ts.pop(self._next_deliver, None)
                    self._next_deliver += 1
                self._deliver_tid = threading.get_ident()
                t0 = time.perf_counter()
                try:
                    self._deliver(arrays)
                finally:
                    self._deliver_tid = None
                    now = time.perf_counter()
                    self._m_pump << (now - t0) * 1e6
                    if dispatched_at is not None:
                        self._m_rtt << (now - dispatched_at) * 1e6
            with self._lock:
                self._inflight -= 1
            self._wbutex.add(1)
            self._wbutex.wake_all()

    def _rows_to_host(self, arrays) -> List[np.ndarray]:
        import jax

        if isinstance(arrays, tuple) and arrays[0] == "host":
            return arrays[1]  # loopback fast path: already host rows
        if self._mesh is None:
            host = np.asarray(jax.device_get(arrays))
            return [host[0], host[1]]
        rows: List[Optional[np.ndarray]] = [None, None]
        for shard in arrays.addressable_shards:
            idx = shard.index[0]
            row = int(idx.start if isinstance(idx, slice) else idx)
            rows[row] = np.asarray(shard.data).reshape(-1)
        return rows  # type: ignore[return-value]

    def _deliver(self, arrays) -> None:
        """One completed exchange: after the permute, side i's device holds
        the PEER's outbound slot — feed it into side i's socket."""
        rows = self._rows_to_host(arrays)
        for side in (0, 1):
            row = rows[side]
            if row is None:
                continue  # not addressable from this host (multi-controller)
            if int(row[0]) != LINK_MAGIC:
                self.fail("bad link slot magic")
                return
            used = int(row[1])
            flags = int(row[4])
            if self.ack_mode == "wire":
                # the peer's cumulative-delivered count rides words 3+5
                # (the piggybacked imm-data ack, 64-bit so it cannot
                # wrap); this is the ONLY credit signal in wire mode
                with self._lock:
                    ack = int(row[3]) | (int(row[5]) << 32)
                    if ack > self._peer_ack:
                        self._peer_ack = ack
            sock = self.socks[side]
            if used:
                self._m_in_bytes << used
            if used and sock is not None:
                # ZERO-copy delivery: the read IOBuf's block wraps the step
                # output's own buffer (external block + release-cb — the
                # HBM-backed IOBuf of the RDMA template, block_pool.h:20-66
                # / iobuf.cpp:258-306); the row stays alive until the last
                # ref drops. Payload bytes materialize once, at the
                # handler/parse boundary.
                base = LINK_HEADER_WORDS * 4
                view = memoryview(row.view(np.uint8))[base : base + used]
                sock._feed(view)
            if flags & F_CLOSE and sock is not None:
                sock.set_failed(ErrorCode.ECLOSE, "peer closed device link")

    def fail(self, reason: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for side in (0, 1):
                self._out[side].clear()
                self._out_nbytes[side] = 0
            self._step_ts.clear()
        link_errors << 1
        self._retire_metrics()
        # party-death feedback for the collective fault plane: a session
        # whose lockstep traffic rode THIS link can never converge once
        # the link is dead — abort it so every party exits with ESESSION
        # (same moment the hooks above retire telemetry)
        try:
            from incubator_brpc_tpu.parallel.mc_dispatch import (
                abort_sessions_for_devices,
            )

            abort_sessions_for_devices(
                [d.id for d in self.devices if d is not None],
                f"device link failed: {reason}",
            )
        except Exception:
            logger.exception("link-death session abort failed")
        self._wbutex.add(1)
        self._wbutex.wake_all()
        for sock in self.socks:
            if sock is not None:
                sock.set_failed(ErrorCode.EFAILEDSOCKET, reason)

    @property
    def inflight_steps(self) -> int:
        with self._lock:
            return self._inflight

    def profile(self) -> dict:
        """Structured snapshot of this link's PR 1 recorders — the
        telemetry that was scrape-only until the topology-aware
        scheduler needed a programmatic read (parallel/mc_dispatch).
        ``gbps`` sums both directions' measured bytes/s; a fresh link
        reads 0.0 until the 1 Hz bvar sampler has a window."""
        out_bps = float(self._m_out_rate.get_value() or 0.0)
        in_bps = float(self._m_in_rate.get_value() or 0.0)
        return {
            "link_id": int(self.link_id),
            "devices": [getattr(d, "id", None) for d in self.devices],
            "rtt_us": float(self._m_rtt.latency()),
            "rtt_p99_us": float(self._m_rtt.latency_percentile(0.99)),
            "steps": int(self._m_rtt.count()),
            "out_bytes_s": out_bps,
            "in_bytes_s": in_bps,
            "out_bytes": int(self._m_out_bytes.get_value()),
            "in_bytes": int(self._m_in_bytes.get_value()),
            "gbps": (out_bps + in_bps) / 1e9,
        }


class DeviceSocket:
    """Socket-shaped endpoint over one side of a DeviceLink: the messenger,
    channel and server paths treat it exactly like a TCP Socket (same duck
    surface), but ``write`` stages bytes onto the link and reads arrive
    from link completions — no fd anywhere."""

    def __init__(
        self,
        link: DeviceLink,
        side: int,
        messenger=None,
        user_message_handler=None,
        context: Optional[dict] = None,
        remote: Optional[EndPoint] = None,
    ):
        from incubator_brpc_tpu.iobuf import IOBuf
        from incubator_brpc_tpu.transport.sock import CONNECTED, _registry

        self.link = link
        self.side = side
        self.messenger = messenger
        self.user_message_handler = user_message_handler
        self.context: dict = dict(context) if context else {}
        dev = link.devices[1 - side]
        self.remote = remote or EndPoint(ip=f"tpu://{getattr(dev, 'id', 0)}", port=0)
        self.state = CONNECTED
        self.error_code = 0
        self.error_text = ""
        self.preferred_protocol = None
        self.is_client = side == 0
        self.inline_read = False
        self.on_failed: List = []
        self.on_revived: List = []
        self._read_buf = IOBuf()
        self._feed_lock = threading.Lock()
        self.id = _registry.insert(self)
        link.attach(side, self)

    # -- write path ----------------------------------------------------------

    def write(
        self,
        data,
        on_error=None,
        timeout: Optional[float] = None,
        drain_inline: bool = False,
    ) -> int:
        from incubator_brpc_tpu.transport.sock import CONNECTED

        # drain_inline is the TCP writer's caller-driven-drain fast path;
        # the link always drains via its own single-drainer step loop, so
        # the hint is accepted (stream writers pass it) and ignored
        if self.state != CONNECTED:
            return ErrorCode.EFAILEDSOCKET
        # bytes and IOBufs both queue zero-copy (the link keeps the IOBuf
        # alive and gathers straight from its block views into the slot).
        # A synchronous failure is reported ONCE, via the return code —
        # the TCP Socket.write contract; also firing on_error would
        # arbitrate the same failure twice (a queued id error delivered
        # at unlock), burning a retry attempt.
        return self.link.send(self.side, data, timeout=timeout)

    # -- read path (driven by link completions) ------------------------------

    def _feed(self, data) -> None:
        """Link delivery: append the byte-stream chunk and run the normal
        messenger cut loop (completions feeding InputMessenger — the
        rdma_completion_queue.cpp:152 shape). A memoryview is wrapped
        zero-copy as an external block (its backing step-output buffer is
        kept alive until the last ref drops); small chunks copy into
        pooled blocks where the external-block bookkeeping would cost more
        than the memcpy."""
        with self._feed_lock:  # per-socket reader serialization
            if isinstance(data, memoryview) and len(data) >= 4096:
                self._read_buf.append_external(data)
            else:
                self._read_buf.append(bytes(data))
            if self.messenger is not None and len(self._read_buf):
                self.messenger.process(self)

    # -- lifecycle -----------------------------------------------------------

    def set_failed(self, code: int = ErrorCode.EFAILEDSOCKET, reason: str = "") -> bool:
        from incubator_brpc_tpu.transport.sock import CONNECTED, FAILED

        if self.state != CONNECTED:
            return False
        self.state = FAILED
        self.error_code = code
        self.error_text = reason
        if code != ErrorCode.ECLOSE:
            self.link.fail(reason)
        else:
            self.link.close(self.side)
        for cb in list(self.on_failed):
            try:
                cb(self)
            except Exception:
                logger.exception("device socket on_failed raised")
        self.link._maybe_retire_metrics()
        return True

    def recycle(self) -> None:
        from incubator_brpc_tpu.transport.sock import RECYCLED, _registry

        if getattr(self, "_recycled", False):
            return  # idempotent: the link map and channels may both settle us
        self._recycled = True
        self.set_failed(ErrorCode.ECLOSE, "recycled")
        self.state = RECYCLED
        _registry.recycle(self.id)

    # sync fast path: a device socket has no fd to poll — callers join
    def try_read_ownership(self) -> bool:
        return False

    def kick_poller(self) -> None:
        pass

    def __repr__(self) -> str:
        return f"<DeviceSocket side={self.side} dev={self.link.devices[self.side]}>"


# -- rendezvous + handshake ---------------------------------------------------


class LinkHub:
    """Cookie rendezvous for link halves. Single-controller JAX: both
    parties live in one process, so the hub is process-global (the
    reference's analog is the rdmacm exchange). A multi-controller
    deployment would rendezvous through the distributed runtime instead —
    the link step itself is already SPMD-dispatchable per host.

    Un-taken cookies expire after ``ttl`` seconds (a client whose
    handshake RPC timed out never collects its link): expiry fails the
    link and recycles its server-side socket so nothing leaks."""

    def __init__(self, ttl: float = 60.0) -> None:
        self._lock = threading.Lock()
        self._links: Dict[str, tuple] = {}  # cookie -> (link, created_ts)
        self._ttl = ttl

    def _prune_locked(self) -> None:
        import time as _time

        now = _time.monotonic()
        for cookie in [
            c for c, (_, ts) in self._links.items() if now - ts > self._ttl
        ]:
            link, _ = self._links.pop(cookie)
            link.fail("handshake abandoned (cookie expired)")
            for sock in link.socks:
                if sock is not None:
                    sock.recycle()

    def create(
        self, cookie: str, devices, slot_words: int, window: int,
        ack_mode: str = "local",
    ) -> DeviceLink:
        import time as _time

        with self._lock:
            self._prune_locked()
            if cookie in self._links:
                raise ValueError("cookie already in use")
            link = DeviceLink(
                devices, slot_words=slot_words, window=window, ack_mode=ack_mode
            )
            self._links[cookie] = (link, _time.monotonic())
            return link

    def take(self, cookie: str) -> Optional[DeviceLink]:
        with self._lock:
            self._prune_locked()
            entry = self._links.pop(cookie, None)
            return entry[0] if entry is not None else None


link_hub = LinkHub()
_cookie_counter = itertools.count(1)


class DeviceLinkMap:
    """Client-side dedup of established device links keyed by
    (endpoint, local device, geometry) — the SocketMap analog for the
    device plane (reference socket_map.h:35 keys connections by
    {EndPoint, rdma, ssl, auth}; rdma_endpoint.h:42-213 runs one QP per
    peer, unbounded peers). Every Channel — single-server, LB-resolved,
    or a PartitionChannel sub-channel — shares ONE link per peer+geometry;
    a dead link is recycled and re-handshaken on the next get. This is
    what turns the two-party DeviceLink into an N-party fabric: a client
    device holds a star of links, one per peer device."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._links: Dict[tuple, "DeviceSocket"] = {}
        # per-endpoint establishment locks; never deleted (deleting a lock
        # another thread holds would let two handshakes race on one key) —
        # bounded by the distinct peers this process ever contacts
        self._key_locks: Dict[tuple, threading.Lock] = {}
        self._cred_refs: Dict[tuple, tuple] = {}  # keep id()-keyed objects alive
        # re-handshake backoff per key: (consecutive_failures,
        # next_allowed_monotonic). A dead peer must not be storm-redialed
        # by every caller that wants the link — failures double the wait
        # (device_link_backoff_initial_ms .. _max_ms), success clears it —
        # the device-plane analog of the circuit breaker's exponential
        # isolation (reference rdma_endpoint re-establishment discipline)
        self._backoff: Dict[tuple, tuple] = {}

    def _key_lock(self, key: tuple) -> threading.Lock:
        with self._lock:
            lk = self._key_locks.get(key)
            if lk is None:
                lk = self._key_locks[key] = threading.Lock()
            return lk

    def get_or_create(
        self,
        ep: EndPoint,
        device_index: int = 0,
        slot_words: int = 16384,
        window: int = 8,
        timeout_ms: float = 60000,
        ack_mode: str = "local",
        controller: str = "single",
        auth=None,
        ssl_context=None,
        ssl_server_hostname=None,
    ) -> "DeviceSocket":
        """``auth``/``ssl_*`` are the calling channel's credentials: the
        handshake must present them (an auth-requiring or TLS server
        rejects a bare bootstrap), and they are part of the link identity —
        channels with different credentials never share a link (the
        reference keys SocketMap by {EndPoint, rdma, ssl, auth},
        socket_map.h:35)."""
        from incubator_brpc_tpu.transport.sock import CONNECTED

        ident = (
            f"auth-{id(auth):x}" if auth is not None else "",
            f"ssl-{id(ssl_context):x}" if ssl_context is not None else "",
            ssl_server_hostname or "",
        )
        key = (
            ep.ip, ep.port, device_index, slot_words, window, ack_mode,
            controller, ident,
        )
        if auth is not None or ssl_context is not None:
            # the key embeds id()s: retain the credential objects for the
            # entry's lifetime, or a GC'd auth object's recycled address
            # would alias a DIFFERENT principal onto this link
            with self._lock:
                self._cred_refs[key] = (auth, ssl_context)
        # per-key lock: a thundering herd to one peer produces ONE
        # handshake, while links to OTHER peers establish concurrently
        with self._key_lock(key):
            with self._lock:
                ds = self._links.get(key)
            if ds is not None and ds.state == CONNECTED:
                return ds
            if ds is not None:
                ds.recycle()  # free the dead link's registry slot
                with self._lock:
                    self._links.pop(key, None)
            # exponential re-handshake backoff: while a recent attempt to
            # this peer failed, refuse instantly instead of dialing — the
            # caller's retry/LB machinery routes around the peer
            from time import monotonic as _mono

            from incubator_brpc_tpu.utils.flags import get_flag as _gf

            with self._lock:
                bo = self._backoff.get(key)
            if bo is not None and _mono() < bo[1]:
                raise ConnectionError(
                    f"device link to {ep.ip}:{ep.port} backing off after "
                    f"{bo[0]} failed handshake(s) "
                    f"({max(0.0, bo[1] - _mono()) * 1e3:.0f} ms left)"
                )
            # The handshake rides a fresh host channel to the peer (the
            # reference's TCP-piggybacked magic+cookie) carrying the
            # caller's credentials; the global client socket map dedupes
            # the underlying TCP connection, so the channel object itself
            # is throwaway — built per establishment, never cached (a
            # cached one would freeze the first caller's timeout forever).
            from incubator_brpc_tpu.rpc.channel import Channel, ChannelOptions

            try:
                boot = Channel()
                if not boot.init(
                    EndPoint(ip=ep.ip, port=ep.port),
                    options=ChannelOptions(
                        timeout_ms=timeout_ms,
                        auth=auth,
                        ssl_context=ssl_context,
                        ssl_server_hostname=ssl_server_hostname,
                    ),
                ):
                    raise ConnectionError(
                        f"device-link bootstrap channel init failed for {ep}"
                    )
                if controller == "multi":
                    from incubator_brpc_tpu.transport.mc_link import (
                        establish_mc_link,
                    )

                    ds = establish_mc_link(
                        boot,
                        device_index=device_index,
                        slot_words=slot_words,
                        window=window,
                        timeout_ms=timeout_ms,
                    )
                else:
                    ds = establish_device_link(
                        boot,
                        device_index=device_index,
                        slot_words=slot_words,
                        window=window,
                        timeout_ms=timeout_ms,
                        ack_mode=ack_mode,
                    )
            except Exception:
                # failed handshake: arm/double the backoff window so the
                # next caller fails fast instead of re-storming the peer
                failures = (bo[0] if bo is not None else 0) + 1
                wait_ms = min(
                    int(_gf("device_link_backoff_initial_ms"))
                    * (2 ** (failures - 1)),
                    int(_gf("device_link_backoff_max_ms")),
                )
                with self._lock:
                    self._backoff[key] = (failures, _mono() + wait_ms / 1e3)
                raise
            with self._lock:
                self._backoff.pop(key, None)  # healthy again
                # opportunistic sweep: recycle dead entries so a long-lived
                # process contacting many ephemeral peers does not
                # accumulate dead sockets in the registry
                for k, old in [
                    (k, v) for k, v in self._links.items() if v.state != CONNECTED
                ]:
                    old.recycle()
                    del self._links[k]
                    if k != key:
                        self._cred_refs.pop(k, None)
                self._links[key] = ds
            return ds

    def live_links(self) -> List["DeviceSocket"]:
        from incubator_brpc_tpu.transport.sock import CONNECTED

        with self._lock:
            return [ds for ds in self._links.values() if ds.state == CONNECTED]

    def link_profile(self) -> Dict[int, dict]:
        """Per-PEER-device snapshot of the live star's measured link
        telemetry: {peer global device id: DeviceLink.profile()}.  This
        is what the topology-aware session scheduler consumes (order
        party fan-out and chunk routes by measured GB/s instead of mesh
        order — TASP) and what ``rpc_view --links`` renders: the
        rtt/bytes-per-second recorders have been live since PR 1, but
        scrape-only.  Two links to one peer device (distinct geometry
        keys) keep the faster-measured entry — the scheduler wants the
        best current estimate of the PEER, not of any one link."""
        prof: Dict[int, dict] = {}
        for ds in self.live_links():
            link = ds.link
            peer = link.devices[1 - ds.side]
            pid = getattr(peer, "id", None)
            if pid is None:
                continue
            p = link.profile()
            have = prof.get(int(pid))
            if have is None or p["gbps"] > have["gbps"]:
                prof[int(pid)] = p
        return prof


device_link_map = DeviceLinkMap()


def link_profile() -> Dict[int, dict]:
    """The process-global star's per-peer telemetry snapshot (see
    :meth:`DeviceLinkMap.link_profile`)."""
    return device_link_map.link_profile()


def make_handshake_handler(server):
    """The server half of the handshake: an ordinary RPC handler on the
    host socket (the TCP-piggybacked magic+cookie of socket.cpp:1692-1704).
    Builds the link + the server-side DeviceSocket bound to this server's
    messenger and method map."""

    def handshake(cntl, request: bytes) -> bytes:
        import jax

        try:
            req = json.loads(request.decode())
        except ValueError as e:
            cntl.set_failed(ErrorCode.EREQUEST, f"bad handshake: {e}")
            return b""
        if not isinstance(req, dict):
            cntl.set_failed(ErrorCode.EREQUEST, "bad handshake: not an object")
            return b""
        if req.get("controller") == "multi":
            # the multi-controller deployment: peer devices live in
            # DIFFERENT processes; the link half built here is lockstep
            # SPMD with the proposer's (transport/mc_link.py)
            from incubator_brpc_tpu.transport.mc_link import (
                accept_mc_handshake,
            )

            return accept_mc_handshake(server, cntl, req)
        try:
            cookie = req["cookie"]
            client_dev = int(req["device"])
            slot_words = int(req.get("slot_words", 16384))
            window = int(req.get("window", 8))
            ack_mode = str(req.get("ack_mode", "local"))
        except (ValueError, KeyError, TypeError) as e:
            cntl.set_failed(ErrorCode.EREQUEST, f"bad handshake: {e}")
            return b""
        devices = jax.devices()
        server_dev = getattr(server.options, "device_index", None)
        if server_dev is None:
            # prefer a device different from the client's (a real second
            # chip / virtual mesh neighbor); fall back to sharing one
            server_dev = (client_dev + 1) % len(devices)
        if client_dev >= len(devices) or server_dev >= len(devices):
            cntl.set_failed(ErrorCode.EREQUEST, "device index out of range")
            return b""
        try:
            link = link_hub.create(
                cookie,
                [devices[client_dev], devices[server_dev]],
                slot_words=slot_words,
                window=window,
                ack_mode=ack_mode,
            )
        except ValueError as e:
            cntl.set_failed(ErrorCode.EREQUEST, str(e))
            return b""
        ds = DeviceSocket(
            link,
            side=1,
            messenger=server._messenger,
            context={"server": server},
        )
        server._device_socks.append(ds)

        def _forget(sock, _server=server):
            # a dead link must not accumulate on a long-running server:
            # drop it from the list and free its registry slot
            try:
                _server._device_socks.remove(sock)
            except ValueError:
                pass
            sock.recycle()

        # fabriclint: allow(lifecycle-callback) self-pruning hook: removes the dead link from the server list and recycles it — firing the hook IS the teardown, and the server fails every device sock at stop
        ds.on_failed.append(_forget)
        return json.dumps(
            {
                "device": server_dev,
                "slot_words": slot_words,
                "window": window,
                # fingerprints of this server's device-kernel methods: the
                # client's fused combo dispatch only lowers a call when the
                # peer advertises the SAME kernel under that name
                "device_methods": {
                    full: dm.fingerprint()
                    for full, dm in getattr(server, "_device_methods", {}).items()
                },
            }
        ).encode()

    return handshake


def establish_device_link(
    channel,
    device_index: int = 0,
    slot_words: int = 16384,
    window: int = 8,
    timeout_ms: float = 60000,
    ack_mode: str = "local",
) -> DeviceSocket:
    """Client half: propose over the host socket, then attach side 0.
    ``channel`` must be an initialized single-server Channel whose normal
    (TCP) path carries the handshake RPC."""
    from incubator_brpc_tpu.rpc.controller import Controller

    cookie = f"link-{next(_cookie_counter)}-{id(channel):x}"
    payload = json.dumps(
        {
            "cookie": cookie,
            "device": device_index,
            "slot_words": slot_words,
            "window": window,
            "ack_mode": ack_mode,
        }
    ).encode()
    cntl = channel._call_host(
        HANDSHAKE_SERVICE,
        HANDSHAKE_METHOD,
        payload,
        cntl=Controller(timeout_ms=timeout_ms),
    )
    if cntl.failed():
        raise ConnectionError(f"device handshake failed: {cntl.error_text}")
    link = link_hub.take(cookie)
    if link is None:
        raise ConnectionError("device handshake succeeded but link not found")
    try:
        advertised = json.loads(cntl.response_payload.decode()).get(
            "device_methods", {}
        )
    except (ValueError, AttributeError):
        advertised = {}
    from incubator_brpc_tpu.rpc import channel as channel_mod

    ds = DeviceSocket(
        link,
        side=0,
        messenger=channel_mod._client_messenger,
    )
    # the peer's device-kernel fingerprints gate the fused combo dispatch
    ds.device_methods = advertised
    return ds
