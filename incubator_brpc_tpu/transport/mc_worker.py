"""Multi-process harness for the multi-controller device plane.

Runnable as ``python -m incubator_brpc_tpu.transport.mc_worker <role> ...``.
Process 0 is an RPC server (and the jax.distributed coordinator); the
last process is the client; each owns ONE local CPU device and the N of
them form an N-device global mesh. Two shapes:

- the two-process PAIR (1 server + 1 client): one link, lockstep SPMD
  exchange (transport/mc_link.py) — the reference RDMA transport's
  deployment (/root/reference/src/brpc/rdma/rdma_endpoint.h:42-213,
  per-host init rdma_helper.cpp);
- the three-process FABRIC (2 servers + 1 fabric-client): a
  PartitionChannel fans one call out over TWO cross-process links — the
  client device holds a star of links, each a 2-device sub-mesh of the
  global group running its own lockstep schedule. The N-party star of
  the single-controller DeviceLinkMap, spanning real processes.

Used by tests/test_mc_link.py and the driver's ``dryrun_multichip``
multi-process gate.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time


# -- the collective-method-plane test kernels ---------------------------------
#
# Module-level so every worker process minting a DeviceMethod from them
# resolves the SAME fingerprint (module.qualname + source + geometry) —
# the property the session accept phase validates. Integer arithmetic
# end-to-end, so results are bit-exact across planes and processes.

SESSION_WIDTH = 512


def _scale_psum_kernel(data, n):
    """psum + elementwise — a user kernel that actually exercises the
    party axis (axis name 'par', shared by the fused single-controller
    dispatch and the mc session plane)."""
    import jax.numpy as jnp
    from jax import lax

    x = data.astype(jnp.int32)
    s = lax.psum(x, "par")
    return ((3 * s + x) % 256).astype(jnp.uint8), n


def _scale_psum_kernel_wrong(data, n):
    """Same name, different body — the divergence the fingerprint check
    must reject before any party enters lockstep."""
    import jax.numpy as jnp
    from jax import lax

    x = data.astype(jnp.int32)
    s = lax.psum(x, "par")
    return ((5 * s + x) % 256).astype(jnp.uint8), n


def session_expected(operands, steps: int, width: int = SESSION_WIDTH):
    """Host-side model of the K-step _scale_psum_kernel chain: exact
    integer arithmetic, so every party's device result must match these
    bytes bit-for-bit."""
    import numpy as np

    rows, ns = [], []
    for op in operands:
        row = np.zeros(width, np.int64)
        row[: len(op)] = np.frombuffer(op, np.uint8)
        rows.append(row)
        ns.append(len(op))
    x = np.stack(rows)
    for _ in range(steps):
        s = x.sum(axis=0)
        x = (3 * s[None, :] + x) % 256
    return [bytes(x[i, : ns[i]].astype(np.uint8)) for i in range(len(rows))]


def _force_local_device_count(n: int) -> None:
    """MUST run before jax backends initialize: each worker owns exactly
    ``n`` local virtual CPU devices (the parent harness may carry an
    8-device XLA_FLAGS from tests/conftest.py — replace, don't append:
    XLA keeps the first occurrence of a duplicated flag)."""
    flags = os.environ.get("XLA_FLAGS", "")
    pat = r"--xla_force_host_platform_device_count=\d+"
    want = f"--xla_force_host_platform_device_count={n}"
    if re.search(pat, flags):
        flags = re.sub(pat, want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags


def _init_distributed(coord_port: int, process_id: int, nprocs: int = 2) -> None:
    import jax

    # this machine's sitecustomize registers the axon TPU plugin; beat it
    # the same way tests/conftest.py does (config wins over env here)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=nprocs,
        process_id=process_id,
    )
    assert len(jax.devices()) == nprocs, (
        f"expected a {nprocs}-device global mesh, got {jax.devices()}"
    )
    assert len(jax.local_devices()) == 1


def run_server(args) -> int:
    _init_distributed(args.coord_port, args.proc_id, args.nprocs)
    import threading

    from incubator_brpc_tpu.rpc import Server, ServerOptions

    # Exit is COORDINATED, not parent-driven: XLA's coordination service
    # runs a cluster-wide shutdown barrier at interpreter exit (jax's
    # atexit), so a worker that exits alone blocks in that barrier until
    # the peer exits too. The client tells us it is done (a plain TCP
    # RPC), we stop, and both processes reach the barrier together.
    quit_ev = threading.Event()

    def _quit(cntl, req: bytes) -> bytes:
        quit_ev.set()
        return b"bye"

    server = Server(ServerOptions(device_index=0))
    served = [0]

    def _echo(cntl, req: bytes) -> bytes:
        served[0] += 1
        if args.die_after_rpcs and served[0] > args.die_after_rpcs:
            # fault injection: the host vanishes mid-request — no
            # response, no close dance, no clean exit (os._exit skips
            # atexit, so not even the coordination service says goodbye)
            print("SERVER_DYING", flush=True)
            os._exit(42)
        return b"echo:" + req

    server.add_service("EchoService", {"Echo": _echo})
    pid = args.proc_id
    server.add_service(
        "part", {"get": lambda cntl, req: b"p%d:" % pid + req}
    )
    # a user-registered device method for the collective method plane:
    # sessions name ("dsvc", "scale") and every party fingerprint-checks
    # it; --wrong-kernel swaps the body to prove the mismatch reject
    from incubator_brpc_tpu.rpc import device_method as _device_method

    kernel = (
        _scale_psum_kernel_wrong if args.wrong_kernel else _scale_psum_kernel
    )
    server.add_service(
        "dsvc",
        # chunkable: psum + elementwise treats every width slice alike
        # and passes n through — chunked overlap sessions are admitted
        {"scale": _device_method(kernel, width=SESSION_WIDTH, chunkable=True)},
    )
    if args.chaos_kill_at_step >= 0:
        # the deterministic chaos drill: this party "dies" at EXACTLY
        # step K of its first session — the RPC server stops (conns
        # fail, so the proposer classifies a connectivity death) while
        # the PROCESS stays alive (the jax.distributed group and the
        # device plane survive, so the healed session can still run and
        # every worker reaches the exit barrier).  The local session is
        # aborted too so this handler unwedges now, not at its deadline.
        from incubator_brpc_tpu.parallel import mc_dispatch as _mcd

        chaos_fired = threading.Event()

        def _chaos_die() -> None:
            print("SERVER_DYING", flush=True)
            server.stop()
            _mcd.abort_sessions_for_owner(
                server, "chaos drill killed this party"
            )

        def _chaos_hook(step: int, own_index: int) -> None:
            if step >= args.chaos_kill_at_step and not chaos_fired.is_set():
                chaos_fired.set()
                threading.Thread(target=_chaos_die, daemon=True).start()
                # park until the stop lands so no further step of the
                # doomed chain dispatches past the kill point
                time.sleep(0.2)

        _mcd.set_step_hook(_chaos_hook)
    server.add_service("Admin", {"Quit": _quit})
    assert server.start(args.rpc_port)
    print(f"SERVER_READY port={server.port}", flush=True)
    # parent closing our stdin is the fallback exit path (client crashed)
    threading.Thread(
        target=lambda: (sys.stdin.read(), quit_ev.set()), daemon=True
    ).start()
    quit_ev.wait()
    server.stop()
    server.join(timeout=10)
    print("SERVER_DONE", flush=True)
    return 0


def run_client(args) -> int:
    _init_distributed(args.coord_port, args.proc_id, args.nprocs)
    from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller

    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{args.rpc_port}",
        options=ChannelOptions(
            transport="tpu",
            link_controller="multi",
            timeout_ms=60000,
            link_slot_words=args.slot_words,
            link_window=args.window,
        ),
    )
    # jax.distributed's init barrier ran, but the peer may not have bound
    # its RPC port yet — retry the first call until the server is up
    # (a refused bootstrap surfaces as a failed controller, not a raise)
    deadline = time.monotonic() + 60.0
    while True:
        first = ch.call_method(
            "EchoService", "Echo", b"hello",
            cntl=Controller(timeout_ms=60000),
        )
        if first.ok():
            break
        if time.monotonic() > deadline:
            print(f"CLIENT_FAIL connect: {first.error_text}", flush=True)
            return 1
        time.sleep(0.2)
    assert first.response_payload == b"echo:hello"

    if args.expect_peer_death:
        return _run_client_peer_death(args, ch)
    for i in range(args.n_rpcs):
        body = bytes((i + j) % 256 for j in range(args.payload))
        req = f"m{i}:".encode() + body
        cntl = ch.call_method(
            "EchoService", "Echo", req, cntl=Controller(timeout_ms=60000)
        )
        assert cntl.ok(), f"echo {i} failed: {cntl.error_text}"
        assert cntl.response_payload == b"echo:" + req, f"echo {i} corrupt"

    link = ch._device_sock.link
    stats = {
        "n_rpcs": args.n_rpcs,
        "payload": args.payload,
        "steps": int(link._seq),
        "peer_ack": int(link.peer_ack),
        "devices": [str(d) for d in link.devices],
        "window": link.window,
        "slot_words": link.slot_words,
    }
    # the cross-host drain signal must actually flow: the peer's
    # cumulative-delivered count rides slot words 3+5 back to us
    assert stats["peer_ack"] > 0, "wire acks never advanced"
    assert stats["steps"] >= args.n_rpcs, "fewer steps than RPCs?"
    # clean shutdown: the close dance agrees on a final step count, both
    # sides dispatch exactly that many, and the link quiesces
    ch._device_sock.recycle()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        with link._lock:
            done = link._closed and link._inflight == 0
        if done:
            break
        time.sleep(0.05)
    assert link._closed, "close dance did not finish"
    stats["final_target"] = link._final_target
    print("CLIENT_OK " + json.dumps(stats), flush=True)
    # release the peer so both processes reach the coordination service's
    # exit barrier together (see run_server)
    _quit_servers([args.rpc_port])
    return 0


def run_fabric_client(args) -> int:
    """Three-process fabric: this client holds TWO multi-controller links
    (one per server process) and a PartitionChannel splits each call
    across them — the N-party star over real processes."""
    _init_distributed(args.coord_port, args.proc_id, args.nprocs)
    from incubator_brpc_tpu.rpc import (
        Channel,
        ChannelOptions,
        Controller,
        PartitionChannel,
    )

    ports = [int(p) for p in args.rpc_ports.split(",")]
    n = len(ports)
    url = "list://" + ",".join(
        f"127.0.0.1:{p} {i}/{n}" for i, p in enumerate(ports)
    )
    pc = PartitionChannel()
    assert pc.init(
        url,
        partition_count=n,
        options=ChannelOptions(
            transport="tpu",
            link_controller="multi",
            timeout_ms=60000,
            link_slot_words=args.slot_words,
            link_window=args.window,
        ),
    )
    expected = b"".join(f"p{i}:X".encode() for i in range(n))
    deadline = time.monotonic() + 90.0
    while True:
        cntl = pc.call_method(
            "part", "get", b"X", cntl=Controller(timeout_ms=60000)
        )
        if cntl.ok() and cntl.response_payload == expected:
            break
        if time.monotonic() > deadline:
            print(f"CLIENT_FAIL fabric: {cntl.error_text}", flush=True)
            return 1
        time.sleep(0.3)
    for i in range(args.n_rpcs):
        body = b"%04d" % i
        cntl = pc.call_method(
            "part", "get", body, cntl=Controller(timeout_ms=60000)
        )
        assert cntl.ok(), f"fabric rpc {i}: {cntl.error_text}"
        want = b"".join(b"p%d:" % j + body for j in range(n))
        assert cntl.response_payload == want, f"fabric rpc {i} merged wrong"
    # pipelined cross-process collective session (mc_collective): all
    # three parties run K lockstep pmean steps, operands device-resident
    # across the chain; every party must converge to the global mean
    coll = None
    if args.collective_steps > 0:
        import jax

        import numpy as _np

        from incubator_brpc_tpu.parallel.mc_collective import (
            expected_mean,
            propose_collective,
        )

        party_ids = sorted(d.id for d in jax.devices())
        client_dev = jax.local_devices()[0].id
        client_index = party_ids.index(client_dev)
        chans = []
        for p in ports:
            hc = Channel()
            assert hc.init(f"127.0.0.1:{p}")
            chans.append(hc)
        out = propose_collective(
            chans, party_ids, client_index,
            steps=args.collective_steps, width=256, seed=7,
        )
        want = expected_mean(7, len(party_ids), 256)
        assert _np.allclose(out["own"], want, atol=1e-5), "no convergence"
        want_sum = float(_np.sum(want, dtype=_np.float64))
        for cs in out["server_checksums"]:
            assert abs(cs - want_sum) < 1e-3, (cs, want_sum)
        coll = {
            "steps": args.collective_steps,
            "per_step_ms": out["elapsed_s"] / args.collective_steps * 1e3,
            "parties": len(party_ids),
        }

    # ParallelChannel lowering THROUGH the collective method plane: the
    # sub-channels resolve to multi-controller links, so the fused path
    # cannot single-dispatch — it schedules a 1-step N-party session of
    # the registered kernel instead (rpc/combo.py -> parallel/mc_dispatch)
    mc_low = None
    if args.mc_lowering_check:
        import numpy as _np2

        from incubator_brpc_tpu.rpc.device_method import (
            DeviceMethod,
            register_device_method,
        )

        # the PROPOSER validates against its local registry too
        register_device_method(
            "dsvc", "scale",
            DeviceMethod(
                _scale_psum_kernel, width=SESSION_WIDTH, chunkable=True
            ),
        )
        req = bytes(range(48))
        cntl = pc.call_method(
            "dsvc", "scale", req, cntl=Controller(timeout_ms=60000)
        )
        assert cntl.ok(), f"mc-lowered call failed: {cntl.error_text}"
        assert getattr(cntl, "collective_fused", False), (
            "mc lowering not taken (fell back to host fan-out)"
        )
        want = b"".join(session_expected([req] * n, steps=1))
        assert cntl.response_payload == want, "mc-lowered merge diverged"
        mc_low = {"bytes": len(cntl.response_payload), "parties": n}

    links = [sub[0]._device_sock.link for sub in pc._subs]
    stats = {
        "n_rpcs": args.n_rpcs,
        "collective": coll,
        "mc_lowered": mc_low,
        "links": [
            {
                "devices": [str(d) for d in lk.devices],
                "steps": int(lk._seq),
                "peer_ack": int(lk.peer_ack),
            }
            for lk in links
        ],
    }
    # one client device, two distinct peer devices: the star
    assert len({l["devices"][0] for l in stats["links"]}) == 1
    assert len({l["devices"][1] for l in stats["links"]}) == len(ports)
    assert all(l["peer_ack"] > 0 for l in stats["links"])
    pc.stop()
    for sub in pc._subs:
        sub[0]._device_sock.recycle()

    def _settled(lk):
        with lk._lock:
            return lk._closed and lk._inflight == 0

    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        if all(_settled(lk) for lk in links):
            break
        time.sleep(0.05)
    assert all(_settled(lk) for lk in links), "a link's close dance hung"
    print("CLIENT_OK " + json.dumps(stats), flush=True)
    _quit_servers(ports)
    return 0


def _connect_all(ports, deadline_s: float = 90.0):
    """One warm host channel per server port, retrying the first echo
    until each server has bound (jax.distributed's init barrier ran, but
    RPC ports come up independently). Returns the channels or None after
    printing CLIENT_FAIL."""
    from incubator_brpc_tpu.rpc import Channel, Controller

    chans = []
    deadline = time.monotonic() + deadline_s
    for p in ports:
        hc = Channel()
        assert hc.init(f"127.0.0.1:{p}")
        while True:
            c = hc.call_method(
                "EchoService", "Echo", b"up", cntl=Controller(timeout_ms=60000)
            )
            if c.ok():
                break
            if time.monotonic() > deadline:
                print(f"CLIENT_FAIL connect {p}: {c.error_text}", flush=True)
                return None
            time.sleep(0.2)
        chans.append(hc)
    return chans


def _quit_servers(ports) -> None:
    """Release every server so all processes reach the coordination
    service's exit barrier together (see run_server) — the one shutdown
    protocol, shared by every client role."""
    from incubator_brpc_tpu.rpc import Channel, Controller

    for p in ports:
        host = Channel()
        assert host.init(f"127.0.0.1:{p}")
        host.call_method("Admin", "Quit", b"", cntl=Controller(timeout_ms=10000))


def run_session_client(args) -> int:
    """N-party collective-method-plane client: propose a K-step session of
    the user-registered ("dsvc", "scale") kernel to every server process
    (plain host channels — no device links needed: the session IS the
    data plane), run our own party's chain, and verify every party's
    result bit-for-bit against the host-side integer model."""
    _init_distributed(args.coord_port, args.proc_id, args.nprocs)
    import jax

    from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
    from incubator_brpc_tpu.rpc.device_method import (
        DeviceMethod,
        register_device_method,
    )

    # the proposer validates (service, method) against its LOCAL registry
    # exactly like every accepting party
    register_device_method(
        "dsvc", "scale",
        DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH, chunkable=True),
    )
    ports = [int(p) for p in args.rpc_ports.split(",")]
    spare_procs = set(
        int(p) for p in args.spare_procs.split(",") if p != ""
    )
    # spare parties stand OUTSIDE the initial session: their devices are
    # excluded from the party set and their channels form the standby
    # pool the elastic recovery path heals dead slots from
    spare_dev_ids = sorted(
        d.id for d in jax.devices() if d.process_index in spare_procs
    )
    party_ids = sorted(
        d.id for d in jax.devices() if d.id not in set(spare_dev_ids)
    )
    client_index = party_ids.index(jax.local_devices()[0].id)
    n = len(party_ids)
    assert len(ports) == n - 1 + len(spare_procs)
    all_chans = _connect_all(ports)
    if all_chans is None:
        return 1
    # server i serves proc i; spare procs' channels leave the party list
    chans = [
        ch for i, ch in enumerate(all_chans) if i not in spare_procs
    ]
    spares = list(
        zip(
            [ch for i, ch in enumerate(all_chans) if i in spare_procs],
            spare_dev_ids,
        )
    )
    # per-party operands with DIFFERENT lengths: proves both the operand
    # routing and the n-passthrough across the chain
    operands = [
        bytes((7 * i + j) % 256 for j in range(64 + 8 * i)) for i in range(n)
    ]
    steps = args.collective_steps or 4
    if args.quantize != "none":
        if args.expect_resume:
            # the quantized elastic drill lives in-process
            # (tests/test_robustness.py); this role is the wire-ratio /
            # error-bound A/B — refuse the combination loudly instead of
            # silently ignoring one flag
            print(
                "CLIENT_FAIL --quantize with --expect-resume is not a "
                "supported role combination",
                flush=True,
            )
            return 1
        return _run_session_client_quantized(
            args, chans, party_ids, client_index, steps, ports
        )
    if args.expect_resume:
        return _run_session_client_resume(
            args, chans, spares, party_ids, client_index, operands, steps,
            ports,
        )
    if args.expect_reject:
        # one server registered a different body under the same name: the
        # accept phase must reject CLEANLY, before any lockstep entry
        try:
            propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=steps, proposer_index=client_index, timeout_ms=60000,
            )
        except RuntimeError as e:
            assert "fingerprint mismatch" in str(e), e
            print(
                "CLIENT_OK " + json.dumps({"rejected": True, "parties": n}),
                flush=True,
            )
            _quit_servers(ports)
            return 0
        print("CLIENT_FAIL mismatch was not rejected", flush=True)
        return 1
    out = propose_dispatch(
        chans, party_ids, "dsvc", "scale", operands,
        steps=steps, proposer_index=client_index, timeout_ms=120000,
        chunks=args.chunks, double_buffer=args.double_buffer,
    )
    want = session_expected(operands, out["final_steps"])
    for i, (got, exp) in enumerate(zip(out["results"], want)):
        assert got == exp, f"party {i} diverged from the integer model"
    stats = {
        "parties": n,
        "steps": out["final_steps"],
        "per_step_ms": out["elapsed_s"] / out["final_steps"] * 1e3,
        "method": "dsvc.scale",
        "chunks": args.chunks,
        "double_buffer": bool(args.double_buffer),
    }
    print("CLIENT_OK " + json.dumps(stats), flush=True)
    _quit_servers(ports)
    return 0


def _run_session_client_quantized(
    args, chans, party_ids, client_index, steps, ports
) -> int:
    """Quantized-collective gate half (--quantize int8|int4): run the
    SAME float32 operands through an EXACT pmean session and a QUANTIZED
    one (interleaved on one fabric), then report the two numbers the
    dryrun gate asserts — bytes-on-wire ratio (quantized / exact, ~0.26x
    for int8, ~0.13x for int4) and the max |quantized - exact| error,
    which must sit inside the documented bound
    (parallel/quantized.pmean_error_bound)."""
    import numpy as np

    from incubator_brpc_tpu.parallel import quantized as _q
    from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm
    from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
    from incubator_brpc_tpu.rpc.device_method import register_device_method

    n = len(party_ids)
    width = SESSION_WIDTH  # 512 B = 128 floats = 4 blocks of 32
    # the proposer resolves (service, method) in its own registry first
    register_device_method("_collective", "pmean", _pmean_dm(width))
    rng = np.random.default_rng(1234)
    rows = [
        (rng.standard_normal(width // 4) * (1.0 + i)).astype(np.float32)
        for i in range(n)
    ]
    operands = [r.tobytes() for r in rows]
    # the overlap schedule rides along when asked (the quantized pmean
    # variants are chunkable; width 512 block-aligns chunks 1/2/4):
    # both arms run the SAME schedule so the A/B isolates quantization
    sched = dict(chunks=args.chunks, double_buffer=args.double_buffer)
    exact = propose_dispatch(
        chans, party_ids, "_collective", "pmean", operands,
        steps=steps, proposer_index=client_index, timeout_ms=120000,
        **sched,
    )
    quant = propose_dispatch(
        chans, party_ids, "_collective", "pmean", operands,
        steps=steps, proposer_index=client_index, timeout_ms=120000,
        quantize=args.quantize, **sched,
    )
    assert quant["final_steps"] == exact["final_steps"]
    bound = _q.pmean_error_bound(rows, exact["final_steps"], args.quantize)
    max_err = 0.0
    for got, ref in zip(quant["results"], exact["results"]):
        qv = np.frombuffer(got, dtype=np.float32)
        ev = np.frombuffer(ref, dtype=np.float32)
        max_err = max(max_err, float(np.abs(qv - ev).max()))
    ratio = quant["wire_bytes"] / exact["wire_bytes"]
    if max_err > bound:
        print(
            f"CLIENT_FAIL quantized error {max_err} above bound {bound}",
            flush=True,
        )
        return 1
    stats = {
        "parties": n,
        "steps": quant["final_steps"],
        "quantize": args.quantize,
        "chunks": args.chunks,
        "double_buffer": bool(args.double_buffer),
        "wire_bytes_exact": exact["wire_bytes"],
        "wire_bytes_quantized": quant["wire_bytes"],
        "wire_ratio": ratio,
        "max_error": max_err,
        "error_bound": bound,
        "method": "_collective.pmean",
    }
    print("CLIENT_OK " + json.dumps(stats), flush=True)
    _quit_servers(ports)
    return 0


def _run_session_client_resume(
    args, chans, spares, party_ids, client_index, operands, steps, ports
) -> int:
    """Chaos-drill client half: one party dies at exactly step K
    (``--chaos-kill-at-step`` on its server); the session must HEAL —
    resume barrier over the survivors, a replacement party filling the
    dead slot, replay from the agreed resume point — and the merged
    result must be byte-identical to an undisturbed run of the same
    operands.  On a TRUE multi-controller fabric the dead party's
    checkpoint ring died with its RPC plane, so the reshard can be
    unreachable and the heal legitimately lands as a full restart over
    the replaced set (``resumed_from`` None): the drill asserts the
    HEAL, and reports the resume point it achieved."""
    from incubator_brpc_tpu.parallel.mc_dispatch import propose_with_recovery

    ckpt = args.checkpoint_every or 2
    out = propose_with_recovery(
        chans, party_ids, "dsvc", "scale", operands,
        steps=steps, proposer_index=client_index, timeout_ms=120000,
        session_deadline_ms=60000, max_reproposals=1,
        spares=spares, checkpoint_every=ckpt,
    )
    want = session_expected(operands, out["final_steps"])
    identical = all(
        got == exp for got, exp in zip(out["results"], want)
    )
    if not identical:
        print("CLIENT_FAIL resumed merge diverged from the model", flush=True)
        return 1
    if not out["replaced_party_ids"]:
        print(
            f"CLIENT_FAIL no heal: replaced={out['replaced_party_ids']} "
            f"resumed_from={out['resumed_from']}",
            flush=True,
        )
        return 1
    stats = {
        "parties": len(party_ids),
        "steps": out["final_steps"],
        # None on a fabric where the dead ring was unreachable (full
        # restart over the replaced set); an int = true checkpoint resume
        "resumed_from": out["resumed_from"],
        "dead_party_ids": out["dead_party_ids"],
        "replaced_party_ids": out["replaced_party_ids"],
        "byte_identical": True,
        "method": "dsvc.scale",
    }
    print("CLIENT_OK " + json.dumps(stats), flush=True)
    _quit_servers(ports)
    return 0


def _free_ports(n: int):
    import socket

    holders, ports = [], []
    for _ in range(n):
        sk = socket.socket()
        sk.bind(("127.0.0.1", 0))
        ports.append(sk.getsockname()[1])
        holders.append(sk)
    for sk in holders:
        sk.close()
    return ports


def _orchestrate(specs, label: str, timeout: float, servers_may_die=False):
    """Shared parent-side runner: spawn every (name, role, proc_id, args)
    worker, collect outputs (client LAST in ``specs`` is the one whose
    CLIENT_OK carries the stats), assert success, return (stats,
    transcript). The exit is worker-coordinated (Admin.Quit + the
    coordination service's barrier); communicate() closing stdin is the
    fallback when the client crashed early."""
    import subprocess

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    for name, role, argv in specs:
        procs.append(
            (
                name,
                subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "incubator_brpc_tpu.transport.mc_worker", role,
                        *argv,
                    ],
                    cwd=repo, env=env, stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                ),
            )
        )
    client_name, client = procs[-1]
    outs = {}
    try:
        outs[client_name], _ = client.communicate(timeout=timeout)
        for name, proc in procs[:-1]:
            outs[name], _ = proc.communicate(timeout=30.0)
    except subprocess.TimeoutExpired:
        for name, proc in procs:
            proc.kill()
        for name, proc in procs:
            if name not in outs:
                outs[name] = (proc.communicate()[0] or "") + " [KILLED]"
        raise AssertionError(
            f"{label} timed out\n"
            + "".join(f"-- {n} --\n{o}\n" for n, o in outs.items())
        )
    transcript = "".join(f"-- {n} --\n{o}\n" for n, o in outs.items())
    assert client.returncode == 0 and "CLIENT_OK" in outs[client_name], (
        f"{label} client failed rc={client.returncode}\n{transcript}"
    )
    if not servers_may_die:
        for name, proc in procs[:-1]:
            assert proc.returncode == 0 and "SERVER_DONE" in outs[name], (
                f"{label} {name} failed rc={proc.returncode}\n{transcript}"
            )
    stats = json.loads(
        outs[client_name].split("CLIENT_OK", 1)[1].strip().splitlines()[0]
    )
    return stats, transcript


def _run_client_peer_death(args, ch) -> int:
    """Fault-injection client half: the peer dies mid-traffic. The link
    must FAIL (fast, via the host socket under the control stream — not a
    2-minute wedge), failing the in-flight RPC, and the dead link must
    not poison the process."""
    from incubator_brpc_tpu.rpc import Controller

    ok_count = 0
    failed_at = None
    for i in range(args.n_rpcs):
        cntl = ch.call_method(
            "EchoService", "Echo", b"f%03d" % i,
            cntl=Controller(timeout_ms=30000, max_retry=0),
        )
        if cntl.ok():
            ok_count += 1
        else:
            failed_at = (i, cntl.error_code, cntl.error_text)
            break
    assert failed_at is not None, "peer died but no RPC ever failed"
    link = ch._device_sock.link
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        with link._lock:
            if link._closed:
                break
        time.sleep(0.05)
    with link._lock:
        closed = link._closed
    assert closed, "link did not fail after peer death"
    from incubator_brpc_tpu.transport.sock import CONNECTED

    assert ch._device_sock.state != CONNECTED
    print(
        "CLIENT_OK "
        + json.dumps(
            {
                "ok_before_death": ok_count,
                "failed_at": failed_at[0],
                "error_code": failed_at[1],
            }
        ),
        flush=True,
    )
    # the peer is dead: the coordination service's exit barrier can never
    # complete, so skip atexit — the CLEAN exit path is covered by the
    # non-fault tests
    sys.stdout.flush()
    os._exit(0)


def orchestrate_pair(extra=(), timeout: float = 240.0):
    """Spawn the server+client pair as real OS processes and collect the
    client's link stats (used by tests/test_mc_link.py and the driver's
    dryrun gate). Returns ``(stats, client_out, server_out)``."""
    coord, rpc = _free_ports(2)
    base = ("--coord-port", str(coord), "--rpc-port", str(rpc))
    stats, transcript = _orchestrate(
        [
            ("server", "server", base),
            ("client", "client", (*base, *extra)),
        ],
        label="two-process pair",
        timeout=timeout,
    )
    return stats, transcript, transcript


def orchestrate_peer_death(die_after: int = 3, timeout: float = 240.0):
    """Fault-injection pair: the SERVER process dies mid-traffic (os._exit
    inside a handler). The client must observe a fast, clean link failure.
    The client doubles as the jax.distributed coordinator here so the
    coordination service survives the death it is reporting on."""
    coord, rpc = _free_ports(2)
    specs = [
        (
            "server",
            "server",
            (
                "--coord-port", str(coord), "--rpc-port", str(rpc),
                "--proc-id", "1",
                "--die-after-rpcs", str(die_after),
            ),
        ),
        (
            "client",
            "client",
            (
                "--coord-port", str(coord), "--rpc-port", str(rpc),
                "--proc-id", "0",
                "--n-rpcs", str(die_after + 20),
                "--expect-peer-death",
            ),
        ),
    ]
    return _orchestrate(
        specs, label="peer-death pair", timeout=timeout, servers_may_die=True
    )


def run_probe(args) -> int:
    """Capability probe body: join the group, run ONE 2-device collective,
    report. Everything the mc plane needs, nothing it doesn't — fails in
    seconds on backends that cannot run multi-process computations."""
    _init_distributed(args.coord_port, args.proc_id, args.nprocs)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = sorted(jax.devices(), key=lambda d: d.id)
    mesh = Mesh(np.asarray(devices), ("p",))
    sharding = NamedSharding(mesh, P("p"))
    own = jax.local_devices()[0]
    idx = [d.id for d in devices].index(own.id)
    fn = jax.jit(
        shard_map_compat(
            lambda x: jax.lax.psum(x, "p"),
            mesh=mesh, in_specs=P("p"), out_specs=P("p"),
        ),
        out_shardings=sharding,
    )
    shard = jax.device_put(jnp.asarray([[float(idx + 1)]]), own)
    x = jax.make_array_from_single_device_arrays(
        (len(devices), 1), sharding, [shard]
    )
    out = fn(x)
    for s in out.addressable_shards:
        total = float(np.asarray(s.data).reshape(-1)[0])
        expect = sum(range(1, len(devices) + 1))
        assert total == expect, (total, expect)
    print("PROBE_OK", flush=True)
    return 0


_mp_capable: dict = {}


def multiprocess_capable(timeout: float = 120.0) -> bool:
    """Fast module-scoped capability gate: can this jax backend run a
    cross-process collective at all? One tiny 2-process psum decides (a
    backend without multi-process computations fails it in seconds);
    cached process-wide so every suite pays at most one probe."""
    if "ok" not in _mp_capable:
        import subprocess

        coord = _free_ports(1)[0]
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-m",
                    "incubator_brpc_tpu.transport.mc_worker", "probe",
                    "--coord-port", str(coord), "--nprocs", "2",
                    "--proc-id", str(i),
                ],
                cwd=repo, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        ok = True
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out = ""
            ok = ok and p.returncode == 0 and "PROBE_OK" in (out or "")
        _mp_capable["ok"] = ok
    return _mp_capable["ok"]


def orchestrate_session(
    n_parties: int = 3,
    steps: int = 4,
    wrong_kernel: bool = False,
    timeout: float = 300.0,
    chunks: int = 1,
    double_buffer: bool = False,
    quantize: str = "none",
):
    """Spawn ``n_parties - 1`` server processes + one session client (all
    one jax.distributed group) and run an N-party collective-method-plane
    session of the user kernel. ``wrong_kernel`` arms ONE server with a
    same-name/different-body kernel so the fingerprint reject path is
    what the run proves. ``chunks``/``double_buffer`` run the session on
    the overlap schedule (chunked sub-collectives, two step slots in
    flight) — byte-identity against the integer model still gates.
    ``quantize`` switches the client to the quantized-pmean A/B role:
    one exact and one quantized session over the same float operands,
    reporting the wire-bytes ratio and the max error vs the documented
    bound (the dryrun quantized gate).  Returns the client's stats."""
    ports = _free_ports(n_parties)
    coord, rpc_ports = ports[0], ports[1:]
    specs = []
    for i in range(n_parties - 1):
        argv = [
            "--coord-port", str(coord), "--nprocs", str(n_parties),
            "--proc-id", str(i), "--rpc-port", str(rpc_ports[i]),
        ]
        if wrong_kernel and i == 0:
            argv.append("--wrong-kernel")
        specs.append((f"server{i}", "server", tuple(argv)))
    client = [
        "--coord-port", str(coord), "--nprocs", str(n_parties),
        "--proc-id", str(n_parties - 1),
        "--rpc-ports", ",".join(map(str, rpc_ports)),
        "--collective-steps", str(steps),
        "--chunks", str(chunks),
        "--quantize", quantize,
    ]
    if double_buffer:
        client.append("--double-buffer")
    if wrong_kernel:
        client.append("--expect-reject")
    specs.append(("session-client", "session-client", tuple(client)))
    return _orchestrate(
        specs, label=f"{n_parties}-party session", timeout=timeout
    )


def orchestrate_chaos_session(
    n_parties: int = 3,
    steps: int = 8,
    kill_at: int = 3,
    checkpoint_every: int = 2,
    timeout: float = 300.0,
):
    """The scriptable chaos drill: ``n_parties - 1`` party servers + ONE
    spare server + the session client, all one jax.distributed group.
    Server 0 is armed with ``--chaos-kill-at-step kill_at`` so exactly
    one party dies at step K of the session; the client runs
    ``propose_with_recovery`` with the spare in its standby pool and
    asserts the session HEALS: replacement joins, resume point agreed
    over the survivors' checkpoints, and the merged result byte-identical
    to an undisturbed run.  Returns the client's stats (resumed_from,
    replaced_party_ids, byte_identical)."""
    n_servers = n_parties  # n_parties - 1 party servers + 1 spare
    ports = _free_ports(n_servers + 1)
    coord, rpc_ports = ports[0], ports[1:]
    nprocs = n_servers + 1
    spare_proc = n_servers - 1  # the LAST server process is the spare
    specs = []
    for i in range(n_servers):
        argv = [
            "--coord-port", str(coord), "--nprocs", str(nprocs),
            "--proc-id", str(i), "--rpc-port", str(rpc_ports[i]),
        ]
        if i == 0:
            argv += ["--chaos-kill-at-step", str(kill_at)]
        specs.append((f"server{i}", "server", tuple(argv)))
    client = [
        "--coord-port", str(coord), "--nprocs", str(nprocs),
        "--proc-id", str(nprocs - 1),
        "--rpc-ports", ",".join(map(str, rpc_ports)),
        "--collective-steps", str(steps),
        "--spare-procs", str(spare_proc),
        "--expect-resume",
        "--checkpoint-every", str(checkpoint_every),
    ]
    specs.append(("session-client", "session-client", tuple(client)))
    return _orchestrate(
        specs,
        label=f"chaos session (kill party 0 at step {kill_at})",
        timeout=timeout,
        servers_may_die=True,
    )


def orchestrate_fabric(n_servers: int = 2, extra=(), timeout: float = 300.0):
    """Spawn ``n_servers`` server processes + one fabric client (all in one
    jax.distributed group) and return the client's per-link stats."""
    ports = _free_ports(n_servers + 1)
    coord, rpc_ports = ports[0], ports[1:]
    nprocs = n_servers + 1
    specs = [
        (
            f"server{i}",
            "server",
            (
                "--coord-port", str(coord), "--nprocs", str(nprocs),
                "--proc-id", str(i), "--rpc-port", str(rpc_ports[i]),
            ),
        )
        for i in range(n_servers)
    ]
    specs.append(
        (
            "fabric-client",
            "fabric-client",
            (
                "--coord-port", str(coord), "--nprocs", str(nprocs),
                "--proc-id", str(n_servers),
                "--rpc-ports", ",".join(map(str, rpc_ports)), *extra,
            ),
        )
    )
    return _orchestrate(specs, label="fabric", timeout=timeout)


def main(argv=None) -> int:
    # SIGUSR1 dumps all thread stacks — the pair runs under an orchestration
    # harness (pytest / dryrun), and a wedged worker must be diagnosable
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "role",
        choices=[
            "server", "client", "fabric-client", "session-client", "probe",
        ],
    )
    ap.add_argument("--coord-port", type=int, required=True)
    ap.add_argument("--rpc-port", type=int, default=0)
    ap.add_argument("--rpc-ports", type=str, default="")  # fabric client
    ap.add_argument("--nprocs", type=int, default=2)
    ap.add_argument("--proc-id", type=int, default=-1)  # -1: by role
    ap.add_argument("--n-rpcs", type=int, default=8)
    ap.add_argument("--payload", type=int, default=3000)
    ap.add_argument("--slot-words", type=int, default=256)
    ap.add_argument("--window", type=int, default=4)
    ap.add_argument("--collective-steps", type=int, default=0)  # fabric
    ap.add_argument("--die-after-rpcs", type=int, default=0)  # server fault
    ap.add_argument("--expect-peer-death", action="store_true")  # client
    # collective method plane (parallel/mc_dispatch):
    ap.add_argument("--wrong-kernel", action="store_true")  # server
    ap.add_argument("--expect-reject", action="store_true")  # session client
    ap.add_argument("--mc-lowering-check", action="store_true")  # fabric
    # elastic sessions (checkpoint/resume + party replacement):
    ap.add_argument("--chaos-kill-at-step", type=int, default=-1)  # server
    ap.add_argument("--spare-procs", type=str, default="")  # session client
    ap.add_argument("--expect-resume", action="store_true")  # session client
    ap.add_argument("--checkpoint-every", type=int, default=0)  # client
    ap.add_argument("--chunks", type=int, default=1)  # session client
    ap.add_argument("--double-buffer", action="store_true")  # session client
    # quantized collectives (parallel/quantized): exact vs int8/int4 A/B
    ap.add_argument(
        "--quantize", choices=["none", "int8", "int4"], default="none"
    )  # session client
    args = ap.parse_args(argv)
    if args.proc_id < 0:
        # pair convention: server is the coordinator, client is last
        args.proc_id = 0 if args.role == "server" else args.nprocs - 1
    _force_local_device_count(1)
    if args.role == "server":
        return run_server(args)
    if args.role == "fabric-client":
        return run_fabric_client(args)
    if args.role == "session-client":
        return run_session_client(args)
    if args.role == "probe":
        return run_probe(args)
    return run_client(args)


if __name__ == "__main__":
    sys.exit(main())
