"""Two-process harness for the multi-controller device plane.

Runnable as ``python -m incubator_brpc_tpu.transport.mc_worker <role> ...``.
One process is the RPC server (and the jax.distributed coordinator), the
other the client; each owns ONE local CPU device and the two form a
2-device global mesh over which the link's exchange step runs lockstep
SPMD (transport/mc_link.py). This is the deployment shape of the
reference's RDMA transport — two real processes, handshake over TCP, data
over the device fabric (/root/reference/src/brpc/rdma/rdma_endpoint.h:
42-213, per-host init rdma_helper.cpp) — used by tests/test_mc_link.py
and the driver's ``dryrun_multichip`` multi-process gate.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time


def _force_local_device_count(n: int) -> None:
    """MUST run before jax backends initialize: each worker owns exactly
    ``n`` local virtual CPU devices (the parent harness may carry an
    8-device XLA_FLAGS from tests/conftest.py — replace, don't append:
    XLA keeps the first occurrence of a duplicated flag)."""
    flags = os.environ.get("XLA_FLAGS", "")
    pat = r"--xla_force_host_platform_device_count=\d+"
    want = f"--xla_force_host_platform_device_count={n}"
    if re.search(pat, flags):
        flags = re.sub(pat, want, flags)
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags


def _init_distributed(coord_port: int, process_id: int) -> None:
    import jax

    # this machine's sitecustomize registers the axon TPU plugin; beat it
    # the same way tests/conftest.py does (config wins over env here)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=2,
        process_id=process_id,
    )
    assert len(jax.devices()) == 2, (
        f"expected a 2-device global mesh, got {jax.devices()}"
    )
    assert len(jax.local_devices()) == 1


def run_server(args) -> int:
    _init_distributed(args.coord_port, process_id=0)
    import threading

    from incubator_brpc_tpu.rpc import Server, ServerOptions

    # Exit is COORDINATED, not parent-driven: XLA's coordination service
    # runs a cluster-wide shutdown barrier at interpreter exit (jax's
    # atexit), so a worker that exits alone blocks in that barrier until
    # the peer exits too. The client tells us it is done (a plain TCP
    # RPC), we stop, and both processes reach the barrier together.
    quit_ev = threading.Event()

    def _quit(cntl, req: bytes) -> bytes:
        quit_ev.set()
        return b"bye"

    server = Server(ServerOptions(device_index=0))
    server.add_service(
        "EchoService", {"Echo": lambda cntl, req: b"echo:" + req}
    )
    server.add_service("Admin", {"Quit": _quit})
    assert server.start(args.rpc_port)
    print(f"SERVER_READY port={server.port}", flush=True)
    # parent closing our stdin is the fallback exit path (client crashed)
    threading.Thread(
        target=lambda: (sys.stdin.read(), quit_ev.set()), daemon=True
    ).start()
    quit_ev.wait()
    server.stop()
    server.join(timeout=10)
    print("SERVER_DONE", flush=True)
    return 0


def run_client(args) -> int:
    _init_distributed(args.coord_port, process_id=1)
    from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller

    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{args.rpc_port}",
        options=ChannelOptions(
            transport="tpu",
            link_controller="multi",
            timeout_ms=60000,
            link_slot_words=args.slot_words,
            link_window=args.window,
        ),
    )
    # jax.distributed's init barrier ran, but the peer may not have bound
    # its RPC port yet — retry the first call until the server is up
    # (a refused bootstrap surfaces as a failed controller, not a raise)
    deadline = time.monotonic() + 60.0
    while True:
        first = ch.call_method(
            "EchoService", "Echo", b"hello",
            cntl=Controller(timeout_ms=60000),
        )
        if first.ok():
            break
        if time.monotonic() > deadline:
            print(f"CLIENT_FAIL connect: {first.error_text}", flush=True)
            return 1
        time.sleep(0.2)
    assert first.response_payload == b"echo:hello"

    for i in range(args.n_rpcs):
        body = bytes((i + j) % 256 for j in range(args.payload))
        req = f"m{i}:".encode() + body
        cntl = ch.call_method(
            "EchoService", "Echo", req, cntl=Controller(timeout_ms=60000)
        )
        assert cntl.ok(), f"echo {i} failed: {cntl.error_text}"
        assert cntl.response_payload == b"echo:" + req, f"echo {i} corrupt"

    link = ch._device_sock.link
    stats = {
        "n_rpcs": args.n_rpcs,
        "payload": args.payload,
        "steps": int(link._seq),
        "peer_ack": int(link.peer_ack),
        "devices": [str(d) for d in link.devices],
        "window": link.window,
        "slot_words": link.slot_words,
    }
    # the cross-host drain signal must actually flow: the peer's
    # cumulative-delivered count rides slot words 3+5 back to us
    assert stats["peer_ack"] > 0, "wire acks never advanced"
    assert stats["steps"] >= args.n_rpcs, "fewer steps than RPCs?"
    # clean shutdown: the close dance agrees on a final step count, both
    # sides dispatch exactly that many, and the link quiesces
    ch._device_sock.recycle()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        with link._lock:
            done = link._closed and link._inflight == 0
        if done:
            break
        time.sleep(0.05)
    assert link._closed, "close dance did not finish"
    stats["final_target"] = link._final_target
    print("CLIENT_OK " + json.dumps(stats), flush=True)
    # release the peer so both processes reach the coordination service's
    # exit barrier together (see run_server)
    host = Channel()
    assert host.init(f"127.0.0.1:{args.rpc_port}")
    host.call_method("Admin", "Quit", b"", cntl=Controller(timeout_ms=10000))
    return 0


def orchestrate_pair(extra=(), timeout: float = 240.0):
    """Spawn the server+client pair as real OS processes and collect the
    client's link stats. The single parent-side runner for both
    tests/test_mc_link.py and the driver's dryrun gate. Returns
    ``(stats, client_out, server_out)``; raises AssertionError with both
    transcripts on any failure."""
    import socket
    import subprocess

    ports = []
    holders = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        holders.append(s)
    for s in holders:
        s.close()
    coord, rpc = ports
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(role, role_extra=()):
        return subprocess.Popen(
            [
                sys.executable, "-m",
                "incubator_brpc_tpu.transport.mc_worker", role,
                "--coord-port", str(coord), "--rpc-port", str(rpc),
                *role_extra,
            ],
            cwd=repo, env=env, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    server = spawn("server")
    client = spawn("client", extra)
    try:
        # the pair self-orchestrates its exit: the client's Admin.Quit
        # releases the server so both reach the coordination service's
        # exit barrier together; communicate() closing the server's
        # stdin is the fallback path when the client crashed early
        client_out, _ = client.communicate(timeout=timeout)
        server_out, _ = server.communicate(timeout=30.0)
    except subprocess.TimeoutExpired:
        client.kill()
        server.kill()
        client_out = (client.communicate()[0] or "") + " [KILLED]"
        server_out = (server.communicate()[0] or "") + " [KILLED]"
        raise AssertionError(
            f"two-process pair timed out\n-- client --\n{client_out}\n"
            f"-- server --\n{server_out}"
        )
    transcript = (
        f"-- client --\n{client_out}\n-- server --\n{server_out}"
    )
    assert client.returncode == 0 and "CLIENT_OK" in client_out, (
        f"client failed rc={client.returncode}\n{transcript}"
    )
    assert server.returncode == 0 and "SERVER_DONE" in server_out, (
        f"server failed rc={server.returncode}\n{transcript}"
    )
    stats = json.loads(
        client_out.split("CLIENT_OK", 1)[1].strip().splitlines()[0]
    )
    return stats, client_out, server_out


def main(argv=None) -> int:
    # SIGUSR1 dumps all thread stacks — the pair runs under an orchestration
    # harness (pytest / dryrun), and a wedged worker must be diagnosable
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    ap = argparse.ArgumentParser()
    ap.add_argument("role", choices=["server", "client"])
    ap.add_argument("--coord-port", type=int, required=True)
    ap.add_argument("--rpc-port", type=int, required=True)
    ap.add_argument("--n-rpcs", type=int, default=8)
    ap.add_argument("--payload", type=int, default=3000)
    ap.add_argument("--slot-words", type=int, default=256)
    ap.add_argument("--window", type=int, default=4)
    args = ap.parse_args(argv)
    _force_local_device_count(1)
    return run_server(args) if args.role == "server" else run_client(args)


if __name__ == "__main__":
    sys.exit(main())
