"""SocketMap — client-side connection dedup (reference
src/brpc/socket_map.{h,cpp}): one main socket per remote endpoint, shared
by every Channel targeting it; failed sockets stay in the map while their
health checker probes (socket_map.cpp:35), so revival is transparent."""

from __future__ import annotations

import threading
from typing import Dict, Optional, Union

from incubator_brpc_tpu.transport.sock import CONNECTED, RECYCLED, Socket
from incubator_brpc_tpu.utils.endpoint import EndPoint, str2endpoint


class SocketMap:
    def __init__(self, messenger=None):
        self._messenger = messenger
        self._lock = threading.Lock()
        self._map: Dict[str, Socket] = {}
        self._pooled: Dict[str, list] = {}  # key -> idle pooled sockets

    def get_or_create(
        self,
        remote: Union[str, EndPoint],
        timeout: float = 5.0,
        key_tag: str = "",
        **kwargs,
    ) -> Socket:
        """``key_tag`` partitions connections the way the reference's
        SocketMapKey{EndPoint, auth, ssl, ...} does (socket_map.h:35): a
        channel with credentials must NOT share a connection with one
        without — the shared socket would be authenticated for both."""
        ep = str2endpoint(remote) if isinstance(remote, str) else remote
        key = f"{ep.ip}:{ep.port}|{key_tag}"
        with self._lock:
            sock = self._map.get(key)
            if sock is not None and sock.state != RECYCLED:
                return sock  # FAILED sockets stay: health check may revive
        # client response processing is framework-only (done callbacks are
        # spawned to the pool), so reads run inline on the reactor
        kwargs.setdefault("inline_read", True)
        sock = Socket.connect(ep, messenger=self._messenger, timeout=timeout, **kwargs)
        with self._lock:
            cur = self._map.get(key)
            if cur is not None and cur.state != RECYCLED:
                sock.recycle()  # lost the race: reuse the established one
                return cur
            self._map[key] = sock
        return sock

    def remove(self, remote: Union[str, EndPoint], key_tag: str = "") -> Optional[Socket]:
        ep = str2endpoint(remote) if isinstance(remote, str) else remote
        key = f"{ep.ip}:{ep.port}|{key_tag}"
        with self._lock:
            return self._map.pop(key, None)

    # -- pooled secondary sockets (reference Socket::GetPooledSocket:
    # an exclusive connection per in-flight call, parked for reuse) -------

    def get_pooled(
        self,
        remote: Union[str, EndPoint],
        timeout: float = 5.0,
        key_tag: str = "",
        **kwargs,
    ) -> Socket:
        """Pop an idle pooled connection or dial a fresh one. The caller
        owns it exclusively until return_pooled()."""
        ep = str2endpoint(remote) if isinstance(remote, str) else remote
        key = f"{ep.ip}:{ep.port}|{key_tag}"
        dead = []
        with self._lock:
            idle = self._pooled.get(key)
            sock = None
            while idle:
                cand = idle.pop()
                if cand.state == CONNECTED:
                    sock = cand
                    break
                dead.append(cand)
        for d in dead:
            d.recycle()  # free the registry slot, don't just drop the ref
        if sock is not None:
            return sock
        # no health checking: a dead pooled connection is simply discarded
        # at the next pop (the pool replaces, it never revives)
        kwargs.setdefault("inline_read", True)
        return Socket.connect(
            ep,
            messenger=self._messenger,
            timeout=timeout,
            health_check_interval=0,
            **kwargs,
        )

    def return_pooled(
        self,
        remote: Union[str, EndPoint],
        sock: Socket,
        key_tag: str = "",
        max_idle: int = 32,
    ) -> None:
        """Park a healthy connection for reuse; broken or surplus ones are
        recycled (the reference caps pooled idle connections too)."""
        if sock.state != CONNECTED:
            sock.recycle()  # free the registry slot
            return
        ep = str2endpoint(remote) if isinstance(remote, str) else remote
        key = f"{ep.ip}:{ep.port}|{key_tag}"
        with self._lock:
            idle = self._pooled.setdefault(key, [])
            if len(idle) < max_idle:
                idle.append(sock)
                return
        sock.recycle()

    def get_short(
        self,
        remote: Union[str, EndPoint],
        timeout: float = 5.0,
        **kwargs,
    ) -> Socket:
        """A fresh connection the caller closes after one call (reference
        Socket::GetShortSocket) — dialed with THIS map's messenger so
        short-connection traffic parses like everything else."""
        ep = str2endpoint(remote) if isinstance(remote, str) else remote
        kwargs.setdefault("inline_read", True)
        return Socket.connect(
            ep,
            messenger=self._messenger,
            timeout=timeout,
            health_check_interval=0,
            **kwargs,
        )

    def recycle_all(self) -> None:
        with self._lock:
            socks, self._map = list(self._map.values()), {}
            for idle in self._pooled.values():
                socks.extend(idle)
            self._pooled = {}
        for s in socks:
            s.recycle()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


_global_map: Optional[SocketMap] = None
_global_lock = threading.Lock()


def global_socket_map() -> SocketMap:
    global _global_map
    if _global_map is None:
        with _global_lock:
            if _global_map is None:
                _global_map = SocketMap()
    return _global_map
