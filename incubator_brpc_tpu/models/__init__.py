"""models — flagship workloads of the fabric.

The reference validates itself with its example pairs (echo_c++,
streaming_echo, parallel_echo — /root/reference/example/); ours are device
workloads:

- ``tensor_echo``: the echo_c++ analog — a fully jitted echo RPC step whose
  payload lives in HBM (framing + checksum + handler + response framing).
- ``fabricnet``: the flagship multi-chip workload — a sharded MoE/pipeline
  network whose forward/backward exercises every combo-channel lowering
  (dp fan-out, tp partition, pp pipeline stream, sp ring, ep all_to_all).
"""

from incubator_brpc_tpu.models.tensor_echo import TensorEchoService, make_echo_step
from incubator_brpc_tpu.models.fabricnet import (
    FabricNetConfig,
    init_params,
    make_train_step,
    make_forward_step,
)

__all__ = [
    "TensorEchoService",
    "make_echo_step",
    "FabricNetConfig",
    "init_params",
    "make_train_step",
    "make_forward_step",
]
