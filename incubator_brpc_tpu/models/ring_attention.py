"""Ring attention — sequence-parallel exact attention over the 'sp' mesh
axis (the long-context slot of the build brief; reference analog: the
Streaming-RPC credit window moving unbounded payloads, SURVEY §2.5 —
here the "stream" is KV blocks rotating around the ICI ring).

Design (Ring Attention with Blockwise Transformers, public recipe):
- Q stays put; each rank's K/V block makes one full trip around the ring
  via ``lax.ppermute`` (one in-flight block per neighbor — the same
  window=1 per-hop ack scheme as RdmaEndpoint's credit flow control).
- Per hop, a blockwise attention step folds into ONLINE-SOFTMAX
  accumulators (running max ``m``, normalizer ``l``, weighted sum ``o``)
  so the result is EXACT full attention without materializing the global
  (T, T) score matrix — memory per rank stays O(T_local^2 / sp).
- Causal masking uses global token positions derived from the rank index,
  so the ring result equals single-device causal attention.

Everything is jittable under shard_map with static shapes; the hop loop
is a ``lax.scan`` (compiler-friendly control flow, no Python loop over
traced values — the whole ring compiles into one XLA while-op with
collective-permute inside).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """Scores for one (Q_local, KV_block) pair + online-softmax pieces.
    q: (B, Tq, H, D), k/v: (B, Tk, H, D), mask: (Tq, Tk) additive."""
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(jnp.float32(d))
    s = s + mask[None, None, :, :]
    m = jnp.max(s, axis=-1)  # (B, H, Tq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B, H, Tq)
    o = jnp.einsum("bhts,bshd->bthd", p, v)  # (B, Tq, H, D)
    return m, l, o


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str = "sp",
    causal: bool = True,
    prefetch: bool = False,
) -> jnp.ndarray:
    """Exact attention with K/V ringing over ``axis``. Call inside
    shard_map with q/k/v sharded on their sequence dim; shapes per rank:
    (B, T_local, H, D). Returns (B, T_local, H, D).

    ``prefetch=True`` emits each hop's ppermute BEFORE the held block's
    attention fold (rotate-while-computing, the T3 overlap shape): the
    next KV block's transfer is independent of the fold, so the compiler
    may overlap the ring hop with the blockwise attention compute
    instead of serializing transfer-then-fold. Bit-identical output —
    the dataflow is unchanged, only the emission order moves."""
    from incubator_brpc_tpu.parallel.compat import axis_size

    sp = axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    b, t, h, d = q.shape
    qf = q.astype(jnp.float32)

    # accumulators: running max m, normalizer l, weighted sum o
    m0 = jnp.full((b, h, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    o0 = jnp.zeros((b, t, h, d), jnp.float32)

    q_pos = idx * t + jnp.arange(t)  # my queries' global positions

    def block_merge(m, l, o, k_r, v_r, r):
        """Fold one held KV block into the online-softmax accumulators.
        The block currently held arrived from rank (idx - r) mod sp."""
        src = (idx - r) % sp
        kv_pos = src * t + jnp.arange(t)
        if causal:
            mask = jnp.where(kv_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF)
        else:
            mask = jnp.zeros((t, t), jnp.float32)
        bm, bl, bo = _block_attn(qf, k_r.astype(jnp.float32),
                                 v_r.astype(jnp.float32), mask)
        # online-softmax merge (flash-style log-sum-exp combination)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)  # rescale old accumulators
        beta = jnp.exp(bm - m_new)  # rescale this block
        l_new = l * alpha + bl * beta
        o_new = (
            o * alpha.transpose(0, 2, 1)[..., None]
            + bo * beta.transpose(0, 2, 1)[..., None]
        )
        return m_new, l_new, o_new

    def hop(carry, r):
        m, l, o, k_r, v_r = carry
        if prefetch:
            # rotate while computing: the transfer of the held block to
            # the right neighbor starts before (independently of) the
            # fold that consumes the SAME held block locally
            k_next = lax.ppermute(k_r, axis, perm)
            v_next = lax.ppermute(v_r, axis, perm)
            m, l, o = block_merge(m, l, o, k_r, v_r, r)
        else:
            m, l, o = block_merge(m, l, o, k_r, v_r, r)
            # pass KV to the right neighbor (window=1 ring stream)
            k_next = lax.ppermute(k_r, axis, perm)
            v_next = lax.ppermute(v_r, axis, perm)
        return (m, l, o, k_next, v_next), None

    # sp-1 hops WITH a permute, then the last held block folds outside the
    # scan: the final rotation's result would be discarded, and XLA cannot
    # DCE a collective inside the while-op — this saves one full KV trip
    if sp > 1:
        (m, l, o, k_last, v_last), _ = lax.scan(
            hop, (m0, l0, o0, k, v), jnp.arange(sp - 1)
        )
    else:
        m, l, o, k_last, v_last = m0, l0, o0, k, v
    m, l, o = block_merge(m, l, o, k_last, v_last, sp - 1)
    # fully-masked rows (never for causal self-attention, where a token
    # always sees itself) would have l == 0; guard the divide anyway
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def full_attention(q, k, v, causal: bool = True):
    """Single-device reference (the spec ring_attention must match)."""
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(jnp.float32(d))
    t = q.shape[1]
    if causal:
        pos = jnp.arange(t)
        s = s + jnp.where(pos[None, :] <= pos[:, None], 0.0, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32)).astype(q.dtype)


def make_ring_attention_step(
    mesh: jax.sharding.Mesh, causal: bool = True, prefetch: bool = False
):
    """Jitted sharded entry: q/k/v sharded over 'sp' on the sequence dim,
    replicated elsewhere (batch could additionally shard over dp/ep —
    kept sequence-only here since this layer IS the sp showcase)."""
    spec = P(None, "sp", None, None)

    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    fn = shard_map_compat(
        partial(ring_attention, axis="sp", causal=causal, prefetch=prefetch),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    jitted = jax.jit(fn)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jitted, place
