"""FabricNet — the flagship multi-chip workload of the fabric.

The reference proves its distribution primitives with the combo-channel
example pairs (example/parallel_echo_c++, partition_echo_c++,
streaming_echo_c++); FabricNet composes *all* of their TPU lowerings into one
training step over the fabric mesh (SURVEY.md §2.5):

- **dp/ep data fan-out + gradient merge** — ParallelChannel scatter/gather
  (parallel_channel.cpp): batch sharded over ('dp','ep'), gradients psummed
  by the shard_map transpose (the ResponseMerger with merger='sum').
- **tp partitioned service** — PartitionChannel (partition_channel.cpp):
  Megatron-style MLP whose hidden dim is sharded over 'tp'; the reply merge
  is a psum riding ICI.
- **pp pipeline** — chained streaming RPC: GPipe microbatch schedule whose
  stage handoff is a ppermute ring over 'pp' (the credit-window stream of
  stream.cpp with window=1 frame in flight per neighbor).
- **sp sequence ring** — with ``heads > 0`` (the default), EXACT causal
  ring attention over 'sp' (models/ring_attention.py: KV blocks rotate the
  ring, online-softmax accumulation — the long-context slot); with
  ``heads == 0`` the lighter ring-mean context pass built on
  parallel.collective.ring_stream.
- **ep expert exchange** — DynamicPartitionChannel
  (partition_channel.h:134): static round-robin token routing via all_to_all
  over 'ep'.

Everything is shard_map'd over the fabric Mesh, static-shaped, and
differentiable — the driver's ``dryrun_multichip`` jits the full train step.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from incubator_brpc_tpu.parallel.compat import axis_size
from jax.sharding import NamedSharding, PartitionSpec as P

from incubator_brpc_tpu.parallel.collective import ring_stream


@dataclasses.dataclass(frozen=True)
class FabricNetConfig:
    d_model: int = 32
    d_ff: int = 64  # sharded over tp — must divide by mesh tp size
    d_expert: int = 32
    experts_per_rank: int = 2
    layers_per_stage: int = 1
    batch: int = 8  # global; must divide by dp*ep*microbatches
    seq: int = 16  # global; must divide by sp
    microbatches: int = 2
    heads: int = 2  # ring-attention heads; 0 = ring-mean context instead
    lr: float = 1e-2
    dtype: jnp.dtype = jnp.float32


def param_specs(heads: int) -> Dict[str, P]:
    """PartitionSpecs for the param pytree (leading 'pp' = pipeline stage)."""
    specs = {
        "w_in": P("pp", None, None, "tp"),
        "w_out": P("pp", None, "tp", None),
        "moe_w1": P("pp", "ep", None, None),
        "moe_w2": P("pp", "ep", None, None),
        "gate": P("pp", None, None),
        "head": P(),
    }
    if heads:
        # attention projections replicated across tp (sp is their axis)
        specs["wqkv"] = P("pp", None, None, None)
        specs["wo"] = P("pp", None, None)
    return specs


def batch_specs() -> Tuple[P, P]:
    x_spec = P(("dp", "ep"), "sp", None)
    return x_spec, x_spec


def init_params(cfg: FabricNetConfig, mesh: jax.sharding.Mesh, seed: int = 0):
    """Initialize the sharded param pytree directly with target shardings so
    XLA materializes each shard on its owner (no host broadcast)."""
    pp = mesh.shape["pp"]
    ep = mesh.shape["ep"]
    d, f, fe = cfg.d_model, cfg.d_ff, cfg.d_expert
    L = cfg.layers_per_stage
    E = cfg.experts_per_rank * ep
    keys = jax.random.split(jax.random.key(seed), 8)
    specs = param_specs(cfg.heads)

    def mk(key, shape, spec, scale):
        # scale is a numpy float64 scalar — multiply in the target dtype or
        # promotion silently upcasts bfloat16 params to float32
        arr = jax.random.normal(key, shape, cfg.dtype) * jnp.asarray(
            scale, cfg.dtype
        )
        return jax.device_put(arr, NamedSharding(mesh, spec))

    params = {
        "w_in": mk(keys[0], (pp, L, d, f), specs["w_in"], 1.0 / np.sqrt(d)),
        "w_out": mk(keys[1], (pp, L, f, d), specs["w_out"], 1.0 / np.sqrt(f)),
        "moe_w1": mk(keys[2], (pp, E, d, fe), specs["moe_w1"], 1.0 / np.sqrt(d)),
        "moe_w2": mk(keys[3], (pp, E, fe, d), specs["moe_w2"], 1.0 / np.sqrt(fe)),
        "gate": mk(keys[4], (pp, d, 1), specs["gate"], 1.0 / np.sqrt(d)),
        "head": mk(keys[5], (d, d), specs["head"], 1.0 / np.sqrt(d)),
    }
    if cfg.heads:
        params["wqkv"] = mk(
            keys[6], (pp, 3, d, d), specs["wqkv"], 1.0 / np.sqrt(d)
        )
        params["wo"] = mk(keys[7], (pp, d, d), specs["wo"], 1.0 / np.sqrt(d))
    return params


def _rms_norm(x: jnp.ndarray) -> jnp.ndarray:
    return x * lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + 1e-6)


def _gelu(x: jnp.ndarray) -> jnp.ndarray:
    """dtype-preserving gelu: jax.nn.gelu's float32 internals promote
    bfloat16 activations, which would poison every downstream matmul (and
    break the pipeline scan whose carry must keep the model dtype)."""
    return jax.nn.gelu(x).astype(x.dtype)


def _mlp_tp(w_in_l, w_out_l, x):
    """Megatron MLP: hidden sharded over 'tp', reply merged with psum —
    the PartitionChannel request/merge path on ICI."""
    h = _gelu(jnp.einsum("bsd,df->bsf", x, w_in_l))
    y = jnp.einsum("bsf,fd->bsd", h, w_out_l)
    return lax.psum(y, "tp")


def _ring_context(x: jnp.ndarray) -> jnp.ndarray:
    """Sequence-parallel global context via the sp ring (streaming RPC
    lowering): fold per-shard sequence means around the ring."""
    sp = axis_size("sp")
    local = jnp.mean(x, axis=1)  # (mb, d)

    def fold(acc, received):
        return acc + received, received

    total, _ = ring_stream(local, "sp", fold, jnp.zeros_like(local))
    return (total / sp)[:, None, :]


def _moe(moe_w1, moe_w2, gate_w, x):
    """Static round-robin MoE over 'ep' — DynamicPartitionChannel lowering.

    Tokens (replicated gate decides magnitude, routing is static round-robin
    by token index) are exchanged with a tiled all_to_all, processed by the
    rank-local experts, and exchanged back (all_to_all is an involution for
    equal tiles).
    """
    ep = axis_size("ep")
    e_local = moe_w1.shape[0]
    mb, sl, d = x.shape
    t = mb * sl
    tokens = x.reshape(t, d)
    g = jax.nn.sigmoid(tokens @ gate_w)  # (t, 1) learned gate
    # group tokens by destination rank (token i -> rank i % ep), chunk-contiguous
    grouped = tokens.reshape(t // ep, ep, d).swapaxes(0, 1).reshape(t, d)
    routed = lax.all_to_all(grouped, "ep", split_axis=0, concat_axis=0, tiled=True)
    # rank-local expert apply: token r -> local expert r % e_local (static)
    xr = routed.reshape(t // e_local, e_local, d).swapaxes(0, 1)  # (e_local, t/e_local, d)
    h = _gelu(jnp.einsum("etd,edf->etf", xr, moe_w1))
    yr = jnp.einsum("etf,efd->etd", h, moe_w2)
    routed_out = yr.swapaxes(0, 1).reshape(t, d)
    back = lax.all_to_all(routed_out, "ep", split_axis=0, concat_axis=0, tiled=True)
    ungrouped = back.reshape(ep, t // ep, d).swapaxes(0, 1).reshape(t, d)
    return (ungrouped * g).reshape(mb, sl, d)


def _ring_attn_block(wqkv, wo, heads, x, prefetch: bool = False):
    """Causal ring attention over 'sp' (models/ring_attention.py) with
    per-stage projections — the long-context sequence-parallel block.
    ``prefetch`` emits each hop's KV rotation before the held block's
    fold (rotate-while-computing; bit-identical output)."""
    from incubator_brpc_tpu.models.ring_attention import ring_attention

    mb, sl, d = x.shape
    q = (x @ wqkv[0]).reshape(mb, sl, heads, d // heads)
    k = (x @ wqkv[1]).reshape(mb, sl, heads, d // heads)
    v = (x @ wqkv[2]).reshape(mb, sl, heads, d // heads)
    out = ring_attention(q, k, v, axis="sp", causal=True, prefetch=prefetch)
    return out.reshape(mb, sl, d) @ wo


def _stage_fn(sp_params, heads, prefetch, x):
    """One pipeline stage: L residual [tp-MLP] layers + sp sequence block
    (ring attention, or ring-mean context when heads=0) + ep MoE block.
    ``heads``/``prefetch`` are static config, threaded via partial — never
    through the (traced-array) param pytree."""
    L = sp_params["w_in"].shape[0]
    for l in range(L):
        x = x + _mlp_tp(sp_params["w_in"][l], sp_params["w_out"][l], _rms_norm(x))
    if heads:
        x = x + _ring_attn_block(
            sp_params["wqkv"], sp_params["wo"], heads, _rms_norm(x),
            prefetch=prefetch,
        )
    else:
        x = x + _ring_context(x)
    x = x + _moe(sp_params["moe_w1"], sp_params["moe_w2"], sp_params["gate"], _rms_norm(x))
    return x


def _pipeline(stage, xs):
    """GPipe over 'pp': scan of M + pp - 1 ticks; stage handoff is a
    ppermute ring (streaming-RPC frame to the right neighbor each tick)."""
    pp = axis_size("pp")
    sidx = lax.axis_index("pp")
    m = xs.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    buf = jnp.zeros_like(xs[0])
    outs = jnp.zeros_like(xs)

    def tick(carry, t):
        buf, outs = carry
        inp = jnp.where(sidx == 0, xs[jnp.clip(t, 0, m - 1)], buf)
        out = stage(inp)
        ot = t - (pp - 1)
        valid = (ot >= 0) & (ot < m) & (sidx == pp - 1)
        outs = jnp.where(valid, outs.at[jnp.clip(ot, 0, m - 1)].set(out), outs)
        buf = lax.ppermute(out, "pp", perm)
        return (buf, outs), None

    (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(m + pp - 1))
    # broadcast last stage's outputs to every pp rank (replicates over pp)
    outs = lax.psum(jnp.where(sidx == pp - 1, outs, jnp.zeros_like(outs)), "pp")
    return outs


def _local_forward(
    cfg: FabricNetConfig, params, x, microbatches: int = 0,
    prefetch: bool = False,
):
    """Per-rank forward body (inside shard_map). x: (B_local, S_local, d).
    ``microbatches`` overrides the config's pipeline microbatch count (the
    overlap schedule feeds one outer slice per inner pipeline fill);
    ``prefetch`` selects the ring attention rotate-while-computing
    emission (bit-identical, see models/ring_attention.py)."""
    # squeeze this rank's pipeline-stage slice (leading pp dim is size 1 here)
    sp_params = {
        "w_in": params["w_in"][0],
        "w_out": params["w_out"][0],
        "moe_w1": params["moe_w1"][0],
        "moe_w2": params["moe_w2"][0],
        "gate": params["gate"][0],
    }
    if cfg.heads:
        sp_params["wqkv"] = params["wqkv"][0]
        sp_params["wo"] = params["wo"][0]
    bl, sl, d = x.shape
    m = microbatches or cfg.microbatches
    xs = x.reshape(m, bl // m, sl, d)
    outs = _pipeline(partial(_stage_fn, sp_params, cfg.heads, prefetch), xs)
    out = outs.reshape(bl, sl, d)
    return out @ params["head"]


def _local_loss(cfg: FabricNetConfig, params, x, y):
    out = _local_forward(cfg, params, x)
    local = jnp.mean(jnp.square(out - y))
    return lax.pmean(local, ("dp", "ep", "sp", "tp", "pp"))


_ALL_AXES = ("dp", "ep", "sp", "tp", "pp")


def _slice_local_loss(cfg: FabricNetConfig, prefetch: bool, params, x, y):
    """One microbatch slice's local loss (inside shard_map): the slice
    pipelines with a single inner microbatch — the outer schedule IS the
    microbatch loop.  ``prefetch`` selects the ring attention
    rotate-while-computing emission (bit-identical)."""
    out = _local_forward(cfg, params, x, microbatches=1, prefetch=prefetch)
    local = jnp.mean(jnp.square(out - y))
    return lax.pmean(local, _ALL_AXES)


def _microbatch_slicer(cfg: FabricNetConfig, mesh: jax.sharding.Mesh):
    """Jitted per-rank reshape (B, S, d) -> (M, B/M, S, d): each rank
    splits its LOCAL batch rows into the M schedule slices — slicing the
    global batch axis outside shard_map would gather a contiguous global
    block that lives on a subset of the dp/ep ranks instead."""
    x_spec, _ = batch_specs()
    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    m_slices = cfg.microbatches

    def body(x):
        bl = x.shape[0]
        return x.reshape(m_slices, bl // m_slices, *x.shape[1:])

    return jax.jit(shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(x_spec,),
        out_specs=P(None, ("dp", "ep"), "sp", None),
    ))


def make_forward_step(cfg: FabricNetConfig, mesh: jax.sharding.Mesh):
    """Jitted sharded forward: (params, x) -> (B, S, d) output."""
    x_spec, _ = batch_specs()
    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    fwd = shard_map_compat(
        partial(_local_forward, cfg),
        mesh=mesh,
        in_specs=(param_specs(cfg.heads), x_spec),
        out_specs=x_spec,
    )
    return jax.jit(fwd)


def make_train_step(
    cfg: FabricNetConfig, mesh: jax.sharding.Mesh, schedule: str = "fused"
):
    """Jitted FULL training step (forward + backward + SGD update) with all
    five parallelism axes live. Returns the jitted step function.

    ``schedule`` selects how gradient collectives meet compute:

    - ``"fused"`` (default, the pre-overlap path unchanged): one
      value_and_grad through the shard_map boundary — the boundary
      transpose emits the gradient psums after the whole backward.
    - ``"serialized"``: the microbatch-sliced A/B baseline — slice m's
      per-leaf gradient psums are barriered before slice m+1's forward
      (compute waits for the full collective, the ~75% MFU shape).
    - ``"overlapped"``: same sliced dataflow with the barrier dropped —
      slice m's chunked psums overlap slice m+1's compute, and ring
      attention prefetches its KV rotation (T3).  Bit-identical loss and
      grads to ``"serialized"``.
    """
    x_spec, y_spec = batch_specs()
    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    if schedule not in ("fused", "serialized", "overlapped"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule != "fused":
        # The T3 microbatch schedule (docs/DEVICE_PLANE.md "overlap
        # scheduler"): grads accumulate per microbatch slice, each
        # slice's gradient reduction firing as per-param-leaf psums at
        # its OWN shard_map boundary transpose — the chunked collective
        # (one sub-collective per leaf, not one fused all-grads psum).
        # "serialized" pins slice m+1's forward behind slice m's psums
        # with an optimization_barrier — the compute-waits-for-full-
        # collective shape fabricnet was stuck at; "overlapped" drops
        # the barrier, so slice m's collectives are dataflow-independent
        # of slice m+1's compute and the scheduler runs them behind it.
        # The barrier is an identity — both schedules run IDENTICAL ops,
        # so loss and grads are bit-identical between them.
        overlap = schedule == "overlapped"
        m_slices = cfg.microbatches
        slice_loss = shard_map_compat(
            partial(_slice_local_loss, cfg, overlap),
            mesh=mesh,
            in_specs=(param_specs(cfg.heads), x_spec, y_spec),
            out_specs=P(),
        )
        grad_fn = jax.value_and_grad(slice_loss)
        slicer = _microbatch_slicer(cfg, mesh)

        def step(params, x, y):
            xs, ys = slicer(x), slicer(y)
            acc = None
            loss_acc = jnp.zeros((), dtype=jnp.float32)
            gate = None  # previous slice's reduced grads
            for m in range(m_slices):
                xm, ym = xs[m], ys[m]
                if gate is not None and not overlap:
                    # serialized: slice m's input becomes data-dependent
                    # on every gradient psum of slice m-1
                    xm, gate = lax.optimization_barrier((xm, gate))
                l_m, g_m = grad_fn(params, xm, ym)
                acc = g_m if acc is None else jax.tree_util.tree_map(
                    jnp.add, acc, g_m
                )
                loss_acc = loss_acc + l_m.astype(jnp.float32)
                gate = g_m
            inv = 1.0 / m_slices
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - cfg.lr * (g * jnp.asarray(inv, g.dtype)
                                           ).astype(p.dtype),
                params, acc,
            )
            return new_params, loss_acc * inv

        return jax.jit(step, donate_argnums=(0,))

    loss_fn = shard_map_compat(
        partial(_local_loss, cfg),
        mesh=mesh,
        in_specs=(param_specs(cfg.heads), x_spec, y_spec),
        out_specs=P(),
    )

    def step(params, x, y):
        loss, grads = jax.value_and_grad(lambda p: loss_fn(p, x, y))(params)
        new_params = jax.tree_util.tree_map(lambda p, g: p - cfg.lr * g, params, grads)
        return new_params, loss

    return jax.jit(step, donate_argnums=(0,))


def make_batch(cfg: FabricNetConfig, mesh: jax.sharding.Mesh, seed: int = 1):
    """Random (x, y) placed with the fabric batch sharding."""
    kx, ky = jax.random.split(jax.random.key(seed))
    x_spec, y_spec = batch_specs()
    shape = (cfg.batch, cfg.seq, cfg.d_model)
    x = jax.device_put(jax.random.normal(kx, shape, cfg.dtype), NamedSharding(mesh, x_spec))
    y = jax.device_put(jax.random.normal(ky, shape, cfg.dtype), NamedSharding(mesh, y_spec))
    return x, y


def validate_config(cfg: FabricNetConfig, mesh: jax.sharding.Mesh) -> None:
    """Static divisibility checks (all shapes must be static for XLA)."""
    dp, pp, tp, sp, ep = (mesh.shape[a] for a in ("dp", "pp", "tp", "sp", "ep"))
    assert cfg.d_ff % tp == 0, "d_ff must divide by tp"
    assert cfg.batch % (dp * ep) == 0, "batch must divide by dp*ep"
    bl = cfg.batch // (dp * ep)
    assert bl % cfg.microbatches == 0, "local batch must divide microbatches"
    assert cfg.seq % sp == 0, "seq must divide by sp"
    if cfg.heads:
        assert cfg.d_model % cfg.heads == 0, "d_model must divide by heads"
    t = (bl // cfg.microbatches) * (cfg.seq // sp)
    assert t % ep == 0, "local tokens must divide by ep"
    assert t % (cfg.experts_per_rank * ep) == 0, "local tokens must divide experts"
