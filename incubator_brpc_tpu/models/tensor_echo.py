"""Tensor echo — the echo_c++ example as a device-resident RPC step.

Reference: example/echo_c++ (EchoService::Echo returns the request string,
optionally with attachment) driven through the client call stack of
SURVEY.md §3.1. Here the whole server-side hot path — parse, verify,
dispatch, handle, respond (baidu_rpc_protocol.cpp:307-503 ProcessRpcRequest →
SendRpcResponse) — is one fused XLA computation over an HBM-resident frame:
no host round-trip per request.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from incubator_brpc_tpu.ops import framing


def _echo_handler(payload: jnp.ndarray) -> jnp.ndarray:
    return payload


class TensorEchoService:
    """Registry of method_id -> jittable handler, mirroring Server's
    _method_map of MethodProperty (reference server.cpp:1209) at device level.

    Handlers must be shape-preserving uint32->uint32 transforms (static
    shapes; XLA traces each handler once per payload geometry).
    """

    def __init__(self) -> None:
        self._methods: Dict[int, Callable[[jnp.ndarray], jnp.ndarray]] = {}
        self.add_method(0, _echo_handler)

    def add_method(self, method_id: int, handler: Callable[[jnp.ndarray], jnp.ndarray]) -> None:
        if method_id in self._methods:
            raise ValueError(f"method {method_id} already registered")
        self._methods[method_id] = handler

    def step(self, framed: jnp.ndarray) -> jnp.ndarray:
        """One server step: parse + verify + dispatch + respond. Jittable.

        Bad frames (magic/checksum mismatch) produce a response frame with
        error_code=EREQUEST and zeroed payload — branch-free, like the
        reference parse returning an error response rather than crashing.
        """
        header, payload, ok = framing.parse(framed)
        # dispatch: method ids may be sparse, so map id -> dense branch index
        # (the reference's FlatMap lookup, server.cpp:1209, becomes an
        # equality-select + lax.switch branch table). Unknown ids produce an
        # ENOMETHOD error frame, mirroring ProcessRpcRequest's lookup failure
        # path (baidu_rpc_protocol.cpp:423-440).
        keys = sorted(self._methods)
        handlers = [self._methods[k] for k in keys]
        mid = header.method_id
        known = jnp.zeros((), bool)
        branch = jnp.zeros((), jnp.int32)
        for i, k in enumerate(keys):
            hit = mid == jnp.uint32(k)
            known = known | hit
            branch = jnp.where(hit, jnp.int32(i), branch)
        if len(handlers) == 1:
            result = handlers[0](payload)
        else:
            result = jax.lax.switch(branch, handlers, payload)
        ok_all = ok & known
        result = jnp.where(ok_all, result, jnp.zeros_like(result))
        err = jnp.where(
            ok,
            jnp.where(known, jnp.uint32(0), jnp.uint32(1002)),  # ENOMETHOD
            jnp.uint32(1003),  # EREQUEST
        )
        return framing.frame(
            result,
            header.correlation_id,
            method_id=header.method_id,
            flags=framing.FLAG_RESPONSE,
            error_code=err,
        )


def make_echo_step(
    payload_words: int = 256,
    service: Optional[TensorEchoService] = None,
):
    """Returns (jitted step fn, example framed request) for a given payload
    geometry — used by bench.py and __graft_entry__.entry()."""
    service = service or TensorEchoService()
    step = jax.jit(service.step)
    payload = jnp.arange(payload_words, dtype=jnp.uint32)
    request = framing.frame(payload, correlation_id=1, method_id=0)
    return step, request
