"""Collective lowerings of combo-channel semantics (used inside shard_map).

Each function is the device-side body of one reference combo channel
(SURVEY.md §2.5). They are thin, composable wrappers over lax collectives so
XLA schedules them on ICI; no Python control flow depends on data.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from incubator_brpc_tpu.parallel.compat import axis_size


def fanout(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """ParallelChannel broadcast side: give every replica along ``axis`` the
    full set of sub-results (reference parallel_channel.cpp CallMapper
    broadcast) — an all_gather over ICI."""
    return lax.all_gather(x, axis)


def merge(x: jnp.ndarray, axis: str, merger: str = "sum") -> jnp.ndarray:
    """ParallelChannel ResponseMerger: combine replies across ``axis``
    (reference parallel_channel.h:92-101). 'sum'|'mean'|'max'|'min'."""
    if merger == "sum":
        return lax.psum(x, axis)
    if merger == "mean":
        return lax.pmean(x, axis)
    if merger == "max":
        return lax.pmax(x, axis)
    if merger == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unknown merger {merger!r}")


def partition_exchange(x: jnp.ndarray, axis: str, split_dim: int = 0, concat_dim: int = 0) -> jnp.ndarray:
    """PartitionChannel: route slice i of every rank to rank i along ``axis``
    (reference partition_channel.cpp tag 'i/N' routing) — all_to_all."""
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)


def ring_stream(
    x: jnp.ndarray,
    axis: str,
    step_fn: Callable[[jnp.ndarray, jnp.ndarray], tuple],
    carry_init: jnp.ndarray,
):
    """Streaming RPC over the ICI ring: pass ``x`` around the ``axis`` ring,
    folding ``step_fn(carry, received) -> (carry, send_next)`` at each hop.

    This is the credit-window tensor stream of SURVEY §2.5 ("bidirectional
    tensor stream over ICI"): the window is implicit — each hop is one
    in-flight frame per neighbor, matching RdmaEndpoint's per-WR ack scheme
    (rdma_endpoint.h:176-195) with window=1.
    """
    n = axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(state, _):
        carry, buf = state
        carry, send = step_fn(carry, buf)
        buf = lax.ppermute(send, axis, perm)
        return (carry, buf), None

    (carry, buf), _ = lax.scan(body, (carry_init, x), None, length=n)
    return carry, buf


def ring_allgather(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """All-gather built from the ring primitive (used by tests to check the
    ring against XLA's native all_gather).

    At hop k each rank holds the chunk that originated at rank (my - k) mod n.
    """
    n = axis_size(axis)
    my = lax.axis_index(axis)
    out = jnp.zeros((n,) + x.shape, x.dtype)

    def step_fn(carry, received):
        acc, k = carry
        src = (my - k) % n
        acc = acc.at[src].set(received)
        return (acc, k + 1), received

    (out, _), _ = ring_stream(x, axis, step_fn, (out, jnp.int32(0)))
    return out
