"""shard_map across JAX generations — one import seam for every plane.

The repo runs on whatever jax the environment bakes in: new builds expose
``jax.shard_map`` and spell the replication check ``check_vma``; older
builds only have ``jax.experimental.shard_map.shard_map`` and spell it
``check_rep`` (and the deprecation shim raises AttributeError for the
public name, so a bare ``jax.shard_map`` call dies at trace time). Every
collective in the tree wants the same thing — "shard_map with the
replication check off, wherever it lives" — so they all route through
here instead of each guessing the API.
"""

from __future__ import annotations


def resolve_shard_map():
    """The callable, wherever this jax build keeps it."""
    try:
        from jax import shard_map  # JAX >= 0.8
        return shard_map
    except (ImportError, AttributeError):  # older JAX (accelerated deprecation)
        from jax.experimental.shard_map import shard_map
        return shard_map


def axis_size(name: str) -> int:
    """Static size of a bound mesh axis, inside shard_map. New jax spells
    it ``lax.axis_size``; older builds special-case ``psum`` of a Python
    constant to the same static int."""
    from jax import lax

    try:
        return lax.axis_size(name)
    except AttributeError:  # pragma: no cover — older JAX
        return lax.psum(1, name)


def shard_map_compat(f, *, mesh, in_specs, out_specs, check: bool = False):
    """``shard_map`` with the replication check spelled for THIS jax.

    ``check=False`` (the default, and what every caller here wants: the
    all-gather/replicated outputs the combo lowerings produce are exactly
    what newer jax cannot statically infer) maps to ``check_vma=False``
    on new builds and ``check_rep=False`` on old ones.
    """
    sm = resolve_shard_map()
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check:
        return sm(f, **kwargs)
    try:
        return sm(f, check_vma=False, **kwargs)
    except TypeError:
        pass
    try:
        return sm(f, check_rep=False, **kwargs)
    except TypeError:  # pragma: no cover — neither spelling: default checks
        return sm(f, **kwargs)
