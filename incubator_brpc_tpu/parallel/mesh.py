"""Fabric mesh — the named device mesh every channel lowering runs over.

The reference addresses peers with EndPoint lists from naming services; the
TPU fabric addresses them with coordinates in a ``jax.sharding.Mesh``. Axis
vocabulary (fixed, sizes may be 1 so every code path exists at any device
count):

    dp — data/replica fan-out (ParallelChannel broadcast+merge)
    pp — pipeline stages (chained streaming RPC)
    tp — tensor/partitioned service shards (PartitionChannel)
    sp — sequence/stream ring (StreamingRPC over ICI neighbors)
    ep — expert/dynamic partition groups (DynamicPartitionChannel)

Shardings are laid out so collectives ride ICI, not DCN (scaling-book
recipe): the innermost axes (tp, sp) map to the fastest mesh dims.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np

FABRIC_AXES = ("dp", "pp", "tp", "sp", "ep")


def default_axis_sizes(n_devices: int) -> Dict[str, int]:
    """Factor ``n_devices`` over the fabric axes.

    Powers of two are split round-robin in priority order dp, tp, pp, sp, ep
    (so 8 devices -> dp2·tp2·pp2, 32 -> all axes 2); any residual odd factor
    lands on dp.
    """
    sizes = {ax: 1 for ax in FABRIC_AXES}
    n = n_devices
    priority = ("dp", "tp", "pp", "sp", "ep")
    while n % 2 == 0 and n > 1:
        for ax in priority:
            if n % 2 != 0 or n == 1:
                break
            sizes[ax] *= 2
            n //= 2
    sizes["dp"] *= n  # odd residue
    return sizes


def make_fabric_mesh(
    n_devices: Optional[int] = None,
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> jax.sharding.Mesh:
    """Build the fabric Mesh. Defaults to all visible devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    if axis_sizes is None:
        axis_sizes = default_axis_sizes(n_devices)
    shape = tuple(axis_sizes.get(ax, 1) for ax in FABRIC_AXES)
    if int(np.prod(shape)) != n_devices:
        raise ValueError(f"axis sizes {axis_sizes} do not factor {n_devices} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return jax.sharding.Mesh(dev_array, FABRIC_AXES)
