"""Collective method plane — ANY registered device method, fabric-wide.

`parallel/mc_collective.py` proved the pipelined cross-controller session
shape (schedule once over the host plane, run K lockstep shard_map steps
with operands device-resident through the chain) — but its kernel was
hardcoded pmean, a canned demo. The single-controller fused dispatch
(`rpc/combo.py`) already runs arbitrary user-registered device methods
(`rpc/device_method.py`) with fingerprint validation, and the mc handshake
advertises those fingerprints (`transport/mc_link.py`) — this module
closes that loop, the way the reference transport carries *arbitrary*
registered methods rather than one canned op (protocol.h:64-158):

- **A session names a (service, method) pair.** The proposal carries the
  pair, the kernel fingerprint the proposer resolved, the row geometry,
  the step count and each party's initial operand. Nothing about the
  kernel's body crosses the wire — only its identity.
- **Every party validates before entering lockstep.** Each party — the
  proposer included — resolves the pair against its LOCAL registry and
  compares fingerprints. A mismatch (same name, different kernel — the
  divergence that would silently corrupt a lockstep chain) is a clean
  reject on the control stream: the proposer surfaces it before any
  party dispatches a collective that could never rendezvous.
- **The shared step binds the resolved kernel.** All parties jit the
  IDENTICAL program: ``shard_map`` over ``Mesh(parties, ("par",))`` —
  the SAME axis name the single-controller fused dispatch binds, so a
  kernel that reduces over the axis (psum gradients, all-to-all experts)
  behaves identically on both planes — applied K times with the chain's
  operands never leaving the devices.
- **N parties, convergent close.** The proposal fans out over the star
  (one host channel per remote party), a barrier collects every accept,
  and the final step count is the monotone max of every party's accept
  target — the 2-party close dance's ``max(targets)`` join generalized
  to N. All parties dispatch exactly ``final`` steps; each run response
  echoes the count and the proposer asserts convergence.

`ParallelChannel._fused_dispatch` lowers through this plane when its
sub-channels resolve to multi-controller links (one shard_map dispatch is
impossible across controllers — the client cannot place bytes on
non-addressable devices), so the single-controller fused path and the
cross-process path present ONE API: register a device method, call the
combo channel, and the transport picks the lowering.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from incubator_brpc_tpu.bvar import Adder, LatencyRecorder, PassiveStatus
from incubator_brpc_tpu.utils.flags import define_flag, get_flag

logger = logging.getLogger(__name__)

define_flag(
    "mc_dispatch_min_steps",
    0,
    "minimum step count this party accepts into a collective-method "
    "session: its accept ack raises the session target to at least this "
    "(the proposer folds every target with max — the N-party join)",
    lambda v: v >= 0,
)

define_flag(
    "mc_dispatch_session_deadline_ms",
    0,
    "default per-session deadline for collective-method sessions: a "
    "session older than this aborts fabric-wide with ESESSION (every "
    "party watches its own copy, so a partitioned party still unwedges); "
    "0 = inherit the proposal's RPC timeout",
    lambda v: v >= 0,
)

define_flag(
    "mc_dispatch_checkpoint_every",
    0,
    "checkpoint cadence (in lockstep steps) for collective-method "
    "sessions: every C completed steps each party retains its "
    "device-resident operand shards in a ring, so an aborted session can "
    "resume from the last COMMON checkpoint instead of step 0; the "
    "proposer stamps the cadence into the run proposal so every party "
    "checkpoints the same steps; 0 = checkpointing off (abort = restart)",
    lambda v: v >= 0,
)

define_flag(
    "mc_dispatch_checkpoint_depth",
    4,
    "ring depth of the per-party checkpoint store: how many checkpointed "
    "steps stay device-resident per session (older entries are evicted "
    "oldest-first; memory cost per entry is parties x width bytes)",
    lambda v: v >= 1,
)

define_flag(
    "mc_dispatch_step_deadline_ms",
    0,
    "per-STEP watchdog for collective-method sessions: a single lockstep "
    "step (dispatch-to-dispatch progress, or the final fetch) stalled "
    "longer than this aborts the session fabric-wide — bounding a wedge "
    "INSIDE one step instead of waiting out the whole session deadline; "
    "0 = off (the session deadline is the only backstop)",
    lambda v: v >= 0,
)

DISPATCH_METHOD = "collective_dispatch"

# Bounds a proposal must sit inside before anything is resolved or run
# (mirrors mc_collective's admission checks).
MAX_STEPS = 100_000
MAX_WIDTH = 1 << 20
MAX_PARTIES = 1024
# chunked overlap sessions (T3): a step's operand may split into at most
# this many independently-dispatched sub-collectives — past ~64 the
# per-chunk dispatch overhead swamps the overlap win (docs/DEVICE_PLANE.md)
MAX_CHUNKS = 64

# plane-level observability: sessions/steps/errors/rejects across every
# kernel, plus a latency summary; per-kernel counters are minted lazily
# below so /vars and /brpc_metrics can tell WHICH methods ride the plane
dispatch_sessions = Adder(name="mc_dispatch_sessions")
dispatch_steps = Adder(name="mc_dispatch_steps")
dispatch_errors = Adder(name="mc_dispatch_errors")
dispatch_rejects = Adder(name="mc_dispatch_rejects")
dispatch_aborts = Adder(name="mc_dispatch_aborts")
dispatch_resumes = Adder(name="mc_dispatch_resumes")
dispatch_replaced_parties = Adder(name="mc_dispatch_replaced_parties")
dispatch_session_us = LatencyRecorder(name="mc_dispatch_session_us")
# the overlap scheduler's proof-of-overlap counters: chunk sub-collectives
# dispatched, and how many of them were dispatched while the SAME slice's
# predecessor collective was still in flight (the non-blocking ack probe
# said not-ready) — their ratio is the measured overlap, scrapeable as
# mc_dispatch_overlap_ratio.  Tallied once per session, not per chunk.
dispatch_chunks = Adder(name="mc_dispatch_chunks")
dispatch_overlapped_chunks = Adder(name="mc_dispatch_overlapped_chunks")
# the quantized-collective plane (parallel/quantized.py): sessions that
# ran a quantized kernel variant, and the cumulative wire bytes the
# quantization removed vs the same session at exact float32 width
# (parties x replayed steps x (width - quantized wire bytes), tallied
# once per session)
dispatch_quantized_sessions = Adder(name="mc_dispatch_quantized_sessions")
dispatch_bytes_saved = Adder(name="mc_dispatch_bytes_saved")


def _overlap_ratio() -> float:
    total = dispatch_chunks.get_value()
    if not total:
        return 0.0
    return dispatch_overlapped_chunks.get_value() / total


overlap_ratio_gauge = PassiveStatus(
    _overlap_ratio, name="mc_dispatch_overlap_ratio"
)

_method_counters: Dict[Tuple[str, str], Adder] = {}
_method_counters_lock = threading.Lock()


def _method_counter(service: str, method: str) -> Adder:
    """Per-kernel session counter (``mc_dispatch_<svc>_<m>_sessions``),
    minted on first use — the bvar registry keeps it scrapeable."""
    key = (service, method)
    with _method_counters_lock:
        ctr = _method_counters.get(key)
        if ctr is None:
            safe = "_".join(
                "".join(c if c.isalnum() else "_" for c in part)
                for part in key
            )
            ctr = Adder(name=f"mc_dispatch_{safe}_sessions")
            _method_counters[key] = ctr
        return ctr


# -- session fault plane -------------------------------------------------------
#
# A session is no longer fire-and-forget: every party (proposer included)
# registers it here with a deadline and an abort event.  Death of a party
# — detected from the proposer's failed run RPC, a dying control socket,
# or a device/mc link's fail() hook — aborts the session FABRIC-WIDE: an
# abort broadcast (phase:"abort") plus each party's own deadline watch
# makes every survivor exit the lockstep chain with a clean ESESSION
# instead of hanging in a barrier the dead party can never join.


class SessionAborted(RuntimeError):
    """A collective session aborted (party death, deadline, or reject).

    ``dead_indexes``/``survivor_indexes`` are party positions in the
    proposal's mesh order — the re-propose path runs the next session
    over exactly ``survivor_indexes``."""

    def __init__(
        self,
        reason: str,
        dead_indexes=(),
        survivor_indexes=(),
        rejects=(),
        session_id: str = "",
        final_steps: int = 0,
    ):
        super().__init__(reason)
        from incubator_brpc_tpu.utils.status import ErrorCode

        self.error_code = int(ErrorCode.ESESSION)
        self.reason = reason
        self.dead_indexes = tuple(dead_indexes)
        self.survivor_indexes = tuple(survivor_indexes)
        self.rejects = tuple(rejects)  # (index, error_text) non-death fails
        # what the resume path needs: the aborted session's identity (its
        # checkpoint rings are keyed on it) and the agreed step count the
        # resumed run must still converge to
        self.session_id = session_id
        self.final_steps = int(final_steps)


class _SessionState:
    __slots__ = (
        "session_id", "party_ids", "owner", "deadline", "abort_event",
        "abort_reason", "aborted", "epoch",
    )

    def __init__(self, session_id, party_ids, deadline, owner, epoch=0):
        self.session_id = session_id
        self.party_ids = tuple(party_ids)
        self.owner = owner  # the serving Server (None on the proposer)
        self.deadline = deadline  # absolute monotonic seconds (0 = none)
        self.abort_event = threading.Event()
        self.abort_reason = ""
        self.aborted = False
        # which RUN of this session this registrant belongs to: a RESUMED
        # run re-registers the SAME session id at epoch+1, and an abort
        # broadcast stamped with an older epoch (a straggler from the
        # aborted first run — delayed delivery, or a retry that rode a
        # fresh connection and lost FIFO with the resume proposal) must
        # not kill the healed run
        self.epoch = int(epoch)


# session id -> every local registrant (proposer AND parties: in a
# single-controller run — and the in-process tests — several parties of
# ONE session live in one process; an abort must unwedge all of them)
_sessions: Dict[str, List[_SessionState]] = {}
_sessions_lock = threading.Lock()

# abort tombstones: session id -> highest epoch aborted SO FAR.  An abort
# only flips registrants that exist when it lands — a run proposal of an
# already-aborted epoch arriving AFTER the abort would otherwise register
# fresh and start a zombie chain no peer will ever join (unwedged only by
# its own deadline).  The tombstone closes that race: such proposals are
# rejected ESESSION at admission.  A RESUMED run (epoch+1) stays
# admissible — the tombstone only covers epochs the proposer already gave
# up on.  Insertion-ordered, capped (dead sessions age out).
_MAX_TOMBSTONES = 256
_aborted_epochs: Dict[str, int] = {}


def aborted_epoch(session_id: str) -> int:
    """Highest aborted epoch for a session (-1 = never aborted here)."""
    with _sessions_lock:
        return _aborted_epochs.get(session_id, -1)


def _register_session(session_id, party_ids, deadline, owner=None, epoch=0):
    st = _SessionState(session_id, party_ids, deadline, owner, epoch=epoch)
    with _sessions_lock:
        _sessions.setdefault(session_id, []).append(st)
    return st


def _unregister_session(st: _SessionState) -> None:
    with _sessions_lock:
        states = _sessions.get(st.session_id)
        if states is not None:
            try:
                states.remove(st)
            except ValueError:
                pass
            if not states:
                del _sessions[st.session_id]


def active_sessions(owner=None) -> int:
    """Live (registered, not yet closed) session registrations — all of
    them, or only those served by ``owner`` (Server.enter_lame_duck
    drains its own)."""
    with _sessions_lock:
        return sum(
            1
            for states in _sessions.values()
            for st in states
            if owner is None or st.owner is owner
        )


def abort_session(
    session_id: str, reason: str, epoch: Optional[int] = None
) -> bool:
    """Flip local registrants of one session to aborted (idempotent;
    counted once per session per process).  ``epoch`` scopes the abort to
    registrants of that run or older — a stale broadcast from an aborted
    first run cannot kill the session's RESUMED run (epoch+1); None
    aborts every registrant (link death, local sweeps).  Returns False
    when nothing matched — already closed, never registered here, or all
    registrants newer than the stamped epoch; all fine for a best-effort
    broadcast."""
    with _sessions_lock:
        states = [
            st
            for st in _sessions.get(session_id, ())
            if epoch is None or st.epoch <= epoch
        ]
        # tombstone the aborted epoch(s): a run proposal for an epoch ≤
        # this arriving LATER (reordered past the abort) must not start a
        # zombie chain.  An epoch-stamped abort tombstones even with no
        # registrant yet — the abort-beats-proposal ordering; an unstamped
        # (local) abort tombstones whatever it actually hit.
        stone = epoch if epoch is not None else max(
            (st.epoch for st in states), default=None
        )
        if stone is not None and _aborted_epochs.get(session_id, -1) < stone:
            while len(_aborted_epochs) >= _MAX_TOMBSTONES:
                _aborted_epochs.pop(next(iter(_aborted_epochs)))
            _aborted_epochs[session_id] = stone
        if not states:
            return False
        first = any(not st.aborted for st in states)
        for st in states:
            st.aborted = True
            if not st.abort_reason:
                st.abort_reason = reason
    if first:
        dispatch_aborts << 1
        logger.warning("mc_dispatch session %s aborted: %s", session_id, reason)
    for st in states:
        st.abort_event.set()
    return True


def abort_sessions_for_owner(owner, reason: str) -> int:
    """Abort every session served by one Server — the chaos drill's
    clean-death seam (a killed party's own handler must unwedge promptly
    instead of burning its session deadline) and a stop-time sweep for
    anything that outlived a drain. Returns the number of sessions hit."""
    with _sessions_lock:
        hit = [
            sid for sid, states in _sessions.items()
            if any(st.owner is owner for st in states)
        ]
    for sid in hit:
        abort_session(sid, reason)
    return len(hit)


def abort_sessions_for_devices(device_ids, reason: str) -> int:
    """Link-death feedback (transport/device_link fail() calls here): any
    active session with a party on one of these GLOBAL device ids aborts —
    the link that carried the lockstep traffic is gone, so the chain can
    never converge. Returns the number of sessions aborted."""
    dead = set(int(d) for d in device_ids)
    with _sessions_lock:
        hit = [
            sid for sid, states in _sessions.items()
            if any(dead & set(st.party_ids) for st in states)
        ]
    for sid in hit:
        abort_session(sid, reason)
    return len(hit)


# -- step-granular checkpoint rings --------------------------------------------
#
# The elastic half of the fault plane: with ``mc_dispatch_checkpoint_every``
# set, each party retains a device-resident ring of its last
# ``mc_dispatch_checkpoint_depth`` completed-step operand shards, keyed by
# (session_id, own party index).  An aborted session's rings survive the
# abort so the resume barrier can agree on the last COMMON checkpointed
# step (the min-join over survivor watermarks) and replay only the steps
# past it.  Rings are released by the proposer's phase:"release" broadcast
# on clean completion (or after a finished resume) and capped by an
# oldest-session eviction so a crashed proposer cannot pin device memory
# forever.  Entries hold the session's GLOBAL jax arrays — retaining them
# is free (no host sync; the buffers just stay alive on their devices).

_MAX_CHECKPOINT_SESSIONS = 16


class _QuantCk:
    """A quantized checkpoint payload: the ring entry of a QUANTIZED
    session stores the block-quantized representation (values + int8
    scale exponents) instead of the float32 rows — the same ~4x the wire
    saves, applied to the ring's device memory (the gauge below reflects
    it).  Power-of-two scales make dequantize→requantize exactly
    idempotent (parallel/quantized.py), so a chain restored from this
    entry replays byte-identically to the undisturbed run."""

    __slots__ = ("q", "e", "mode", "block", "width")

    def __init__(self, q, e, mode: str, block: int, width: int):
        self.q = q
        self.e = e
        self.mode = mode
        self.block = int(block)
        self.width = int(width)

    def arrays(self):
        return (self.q, self.e)

    def shard_row(self, dev):
        """Materialize the full-width uint8 row retained for one device
        (host-side dequantize via the numpy twin — bitwise equal to the
        jax arithmetic, resume-path only), or None when this payload
        holds no shard on that device."""
        from incubator_brpc_tpu.parallel import quantized as _quantized

        q_sh = next(
            (s for s in self.q.addressable_shards if s.device == dev), None
        )
        e_sh = next(
            (s for s in self.e.addressable_shards if s.device == dev), None
        )
        if q_sh is None or e_sh is None:
            return None
        f = _quantized.np_dequantize(
            np.asarray(q_sh.data).reshape(-1),
            np.asarray(e_sh.data).reshape(-1),
            self.mode,
            self.block,
        )
        row = np.frombuffer(f.astype(np.float32).tobytes(), dtype=np.uint8)
        return row.copy()


def _payload_arrays(payload):
    """The jax arrays inside a ring payload — raw row array, or the
    quantized pair — for readiness probes."""
    if isinstance(payload, _QuantCk):
        return payload.arrays()
    return (payload,)


def _payload_shard_row(payload, dev) -> Optional[np.ndarray]:
    """Full-width uint8 row this payload retains on one device, or
    None.  One accessor for both entry formats so the reshard and
    restore paths cannot diverge on representation."""
    if isinstance(payload, _QuantCk):
        return payload.shard_row(dev)
    sh = next(
        (s for s in payload.addressable_shards if s.device == dev), None
    )
    if sh is None:
        return None
    return np.asarray(sh.data).reshape(-1).astype(np.uint8)


class _CheckpointRing:
    __slots__ = ("session_id", "own_index", "party_ids", "entries",
                 "entry_bytes")

    def __init__(self, session_id, own_index, party_ids, entry_bytes):
        self.session_id = session_id
        self.own_index = int(own_index)
        self.party_ids = tuple(party_ids)
        self.entries = []  # ascending [(completed_step, x, ns)]
        self.entry_bytes = int(entry_bytes)  # retained bytes per entry

    def put(self, step: int, x, ns, depth: int) -> None:
        # a RESUMED run replays step numbers the aborted run already
        # checkpointed: the fresh entry REPLACES the stale one (which may
        # be wedged behind the dead party's collective and never become
        # ready) — duplicates would make get() hand back the stale arrays
        step = int(step)
        self.entries = [e for e in self.entries if e[0] != step]
        self.entries.append((step, x, ns))
        self.entries.sort(key=lambda e: e[0])
        while len(self.entries) > depth:
            self.entries.pop(0)

    @staticmethod
    def _ready(x, ns) -> bool:
        """Checkpoints are retained at DISPATCH time (the chain is
        async); an entry only counts toward the resume census once its
        buffers are actually computed — a step wedged behind a dead
        party's collective must never be elected as the resume point
        (materializing it would hang the resume barrier itself)."""
        for arr in (*_payload_arrays(x), ns):
            fn = getattr(arr, "is_ready", None)
            if callable(fn):
                try:
                    if not fn():
                        return False
                except Exception:  # noqa: BLE001 — runtime quirk: count it
                    pass
        return True

    def watermark(self) -> int:
        steps = self.steps()
        return max(steps) if steps else 0

    def steps(self):
        return [s for s, x, n in self.entries if self._ready(x, n)]

    def get(self, step: int):
        for s, x, ns in self.entries:
            if s == step:
                return x, ns
        return None


# session id -> {own_index: ring}; insertion-ordered for eviction
_checkpoints: Dict[str, Dict[int, _CheckpointRing]] = {}
_checkpoints_lock = threading.Lock()


def _checkpoint_ring(session_id, own_index, party_ids, entry_bytes):
    """Get-or-create the ring for one party of one session (evicting the
    oldest session past the cap — bounded device memory, not a leak).
    Eviction prefers sessions with no LIVE registrant: a churning fleet
    of short sessions must not silently strip a long-running session of
    the very checkpoints its resume depends on.  (The live set is
    snapshotted before taking the ring lock — no lock nesting.)"""
    with _sessions_lock:
        live = set(_sessions)
    with _checkpoints_lock:
        rings = _checkpoints.get(session_id)
        if rings is None:
            while len(_checkpoints) >= _MAX_CHECKPOINT_SESSIONS:
                victim = next(
                    (s for s in _checkpoints if s not in live),
                    next(iter(_checkpoints)),  # all live: cap still wins
                )
                _checkpoints.pop(victim)
            rings = _checkpoints.setdefault(session_id, {})
        ring = rings.get(int(own_index))
        if ring is None:
            ring = _CheckpointRing(
                session_id, own_index, party_ids, entry_bytes
            )
            rings[int(own_index)] = ring
        return ring


def _checkpoint_lookup(session_id, own_index):
    with _checkpoints_lock:
        return _checkpoints.get(session_id, {}).get(int(own_index))


def checkpoint_watermarks(session_id: str) -> Dict[int, dict]:
    """Every LOCAL party's checkpoint census for one session — what a
    phase:"resume_query" answers: {party index: {"watermark": last
    checkpointed step, "steps": retained steps}}."""
    with _checkpoints_lock:
        rings = list(_checkpoints.get(session_id, {}).values())
    return {
        r.own_index: {"watermark": r.watermark(), "steps": r.steps()}
        for r in rings
    }


def release_checkpoints(session_id: str) -> bool:
    """Drop every local ring of one session (the proposer broadcasts this
    on clean completion; idempotent)."""
    with _checkpoints_lock:
        return _checkpoints.pop(session_id, None) is not None


def checkpoint_bytes_retained() -> int:
    """Device bytes pinned by checkpoint rings across every session —
    the cost side of the checkpoint-depth tradeoff, scrapeable."""
    with _checkpoints_lock:
        return sum(
            len(r.entries) * r.entry_bytes
            for rings in _checkpoints.values()
            for r in rings.values()
        )


checkpoint_bytes_gauge = PassiveStatus(
    checkpoint_bytes_retained, name="mc_dispatch_checkpoint_bytes"
)


def _checkpoint_rows(
    session_id: str, step: int, slots
) -> Dict[int, Tuple[bytes, int]]:
    """Materialize checkpointed rows for the requested party slots at one
    step, from ANY local ring that addresses them — the reshard source a
    replacement party is bootstrapped from.  Returns {slot: (full-width
    row bytes, n)} for every slot this process can serve (possibly
    empty).  This is the one host-blocking checkpoint operation, and it
    only runs on the resume path."""
    import jax  # noqa: F401 — device access below

    want = [int(s) for s in slots]
    with _checkpoints_lock:
        rings = list(_checkpoints.get(session_id, {}).values())
    out: Dict[int, Tuple[bytes, int]] = {}
    for ring in rings:
        entry = ring.get(int(step))
        if entry is None:
            continue
        x, ns = entry
        by_dev_n = {s.device: s for s in ns.addressable_shards}
        for slot in want:
            if slot in out or not (0 <= slot < len(ring.party_ids)):
                continue
            try:
                dev = _devices_by_id([ring.party_ids[slot]])[0]
            except ValueError:
                continue
            # the wire format is always the FULL-WIDTH row: a quantized
            # ring dequantizes here (exact — power-of-two scales), so
            # the reshard protocol never forks on representation
            row = _payload_shard_row(x, dev)
            sn = by_dev_n.get(dev)
            if row is None or sn is None:
                continue
            out[slot] = (
                row.tobytes(),
                int(np.asarray(sn.data).reshape(-1)[0]),
            )
    return out


def checkpoint_fetch(session_id: str, step: int, slots) -> Dict[int, dict]:
    """The wire form of :func:`_checkpoint_rows` (phase:"fetch_shard"):
    {slot: {"row": b64 full-width row bytes, "n": int}}."""
    return {
        slot: {"row": base64.b64encode(row).decode(), "n": int(n)}
        for slot, (row, n) in _checkpoint_rows(session_id, step, slots).items()
    }


def resume_point(watermarks: Dict[int, Optional[dict]]) -> int:
    """The resume barrier's join: the last COMMON checkpointed step over
    the survivors — ``min`` over their watermarks, the dual of the accept
    phase's ``max`` join (a session can only resume from a step EVERY
    survivor retained, just as it can only run a count every party
    accepted).  ``watermarks[slot]`` is a resume_query answer ({"watermark",
    "steps"}) or None for a survivor that answered nothing.  Any survivor
    with no checkpoint drags the join to 0 — the full-restart fallback.
    The min is validated against every retained set (rings are
    cadence-uniform, but an evicted entry must not be resumed from): when
    the min is not common, the join falls back to the deepest step ALL
    survivors still retain, then to 0."""
    if not watermarks:
        return 0
    infos = list(watermarks.values())
    if any(not info or int(info.get("watermark", 0)) <= 0 for info in infos):
        return 0
    point = min(int(info["watermark"]) for info in infos)
    sets = [frozenset(int(s) for s in info.get("steps", ())) for info in infos]
    if all(point in s for s in sets):
        return point
    common = frozenset.intersection(*sets) if sets else frozenset()
    return max((s for s in common if s <= point), default=0)


# Between-step seam: chaos drills park parties here (deterministically
# mid-session) and production leaves it None.  Called as fn(step_index)
# — or fn(step_index, own_index) / fn(step_index, own_index, chunk) when
# it accepts more arguments, so a drill can target ONE party, or one
# CHUNK of a step (half-acked-step chaos) — before each lockstep step
# (1/2-arg forms fire once per step; the 3-arg form fires before every
# chunk dispatch of a chunked overlap session).
_step_hook: Optional[Callable] = None


def set_step_hook(fn: Optional[Callable]) -> None:
    global _step_hook
    if fn is not None:
        import inspect

        try:
            nparams = len(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            nparams = 1
        if nparams < 2:
            inner1 = fn
            fn = (  # noqa: E731
                lambda step, idx, chunk, _f=inner1:
                _f(step) if chunk == 0 else None
            )
        elif nparams < 3:
            inner2 = fn
            fn = (  # noqa: E731
                lambda step, idx, chunk, _f=inner2:
                _f(step, idx) if chunk == 0 else None
            )
    _step_hook = fn


# -- kernel resolution ---------------------------------------------------------

# Fallback resolvers for builtin kernels that are minted per-geometry
# rather than registered by a Server (mc_collective's pmean installs one).
# Signature: (service, method, width_bytes) -> Optional[DeviceMethod].
_resolvers: List[Callable] = []


def register_method_resolver(fn: Callable) -> None:
    if fn not in _resolvers:
        _resolvers.append(fn)


def resolve_method(service: str, method: str, width: Optional[int] = None):
    """Resolve (service, method) to this process's DeviceMethod: the
    process-global registry first (what Server.add_service fills), then
    the builtin resolvers. ``width`` (row bytes) must match the resolved
    geometry — a session whose parties disagree on geometry could never
    exchange shards."""
    from incubator_brpc_tpu.rpc.device_method import lookup_device_method

    dm = lookup_device_method(service, method)
    if dm is None:
        for r in list(_resolvers):
            dm = r(service, method, width)
            if dm is not None:
                break
    if dm is None:
        return None
    if width is not None and dm.width != width:
        return None
    return dm


def _devices_by_id(ids: List[int]):
    import jax

    by_id = {d.id: d for d in jax.devices()}
    try:
        return [by_id[i] for i in ids]
    except KeyError as e:
        raise ValueError(
            f"device id {e} not in this process's global view "
            f"(is jax.distributed initialized everywhere?)"
        )


# -- the shared lockstep step --------------------------------------------------


_step_cache: Dict[tuple, tuple] = {}  # (fp, party ids) -> (step_fn, dm)
# chunk split/concat programs: (party ids, width, chunks) -> (split, concat)
_chunk_ops_cache: Dict[tuple, tuple] = {}
# checkpoint quantizers: (party ids, width, mode, block) -> jitted qz
_ck_quant_cache: Dict[tuple, object] = {}
_step_cache_lock = threading.Lock()  # guards ALL three caches (never nested)


def _make_step(dm, mesh, sharding, party_ids):
    """The identical jitted program every party dispatches: one shard_map
    application of the resolved kernel over the party axis. Axis name
    "par" matches the single-controller fused dispatch (rpc/combo.py), so
    axis-reducing kernels produce the same bytes on both planes. Cached
    per (kernel fingerprint, party set): the ParallelChannel lowering
    runs one session per combo CALL, and re-tracing every call would put
    XLA compilation on the request path (combo's _fused_cache, here).
    Overlap sessions call the SAME cached program at a chunk's width —
    jit re-specializes per input shape, and a chunk-safe kernel applied
    to a slice yields the slice of the full-width result, so the chunked
    chain's bytes match the unchunked chain's."""
    import jax
    from jax.sharding import PartitionSpec as P

    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    key = (dm.fingerprint(), tuple(party_ids))
    with _step_cache_lock:
        cached = _step_cache.get(key)
        if cached is not None and cached[1] is not dm:
            cached = None  # same name re-registered with a new DeviceMethod
        if cached is None:

            def body(data, ns):
                out, m = dm.kernel(data[0], ns[0])
                return out[None], m[None]

            wrapped = shard_map_compat(
                body, mesh=mesh, in_specs=(P("par"), P("par")),
                out_specs=(P("par"), P("par")),
            )
            cached = (
                jax.jit(wrapped, out_shardings=(sharding, sharding)), dm
            )
            _step_cache[key] = cached
    return cached[0]


def _make_chunk_ops(mesh, sharding, width: int, chunks: int, party_ids):
    """Jitted split/concat between the full-width session row and its C
    leading-axis chunks.  Pure per-shard slicing — NO collectives, so the
    parties need no rendezvous to run them, and both directions dispatch
    async (the operands never leave their devices).  Cached like the step
    program: re-tracing per session would put XLA on the request path."""
    import jax
    import jax.numpy as jnp

    key = (tuple(party_ids), int(width), int(chunks))
    with _step_cache_lock:
        cached = _chunk_ops_cache.get(key)
        if cached is None:
            cw = width // chunks

            def split(full):
                return tuple(
                    full[:, j * cw:(j + 1) * cw] for j in range(chunks)
                )

            def concat(*parts):
                return jnp.concatenate(parts, axis=1)

            cached = (
                jax.jit(split, out_shardings=(sharding,) * chunks),
                jax.jit(concat, out_shardings=sharding),
            )
            _chunk_ops_cache[key] = cached
    return cached


def _make_ck_quant(mesh, sharding, dm, party_ids):
    """Jitted checkpoint quantizer for a quantized session: global uint8
    rows (n, width) -> (wire values, int8 exponents), both sharded over
    the party axis.  Pure per-row arithmetic — no collectives, so the
    parties need no rendezvous and the dispatch stays async (retaining
    the quantized arrays IS the checkpoint, same as the raw path).
    Cached like the step program."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from incubator_brpc_tpu.parallel.quantized import _jq_quantize

    mode, block = dm.quant_mode, dm.quant_block
    key = (tuple(party_ids), int(dm.width), mode, int(block))
    with _step_cache_lock:
        cached = _ck_quant_cache.get(key)
        if cached is None:
            out_sh = NamedSharding(mesh, P("par"))

            def qz(x, _m=mode, _b=block):
                import jax.numpy as jnp

                f = jax.lax.bitcast_convert_type(
                    x.reshape(x.shape[0], -1, 4), jnp.float32
                )
                return _jq_quantize(f, _m, _b)

            cached = jax.jit(qz, out_shardings=(out_sh, out_sh))
            _ck_quant_cache[key] = cached
    return cached


# fabriclint: hotpath
def _chunk_ready(arr) -> bool:
    """Non-blocking chunk-ack probe — the overlap scheduler's per-chunk
    observation point.  Reads the buffer's completion state without
    synchronizing (``jax.Array.is_ready``); a runtime without the probe
    reports ready, which only degrades telemetry, never correctness (the
    device executes the chunk chain in dataflow order regardless)."""
    fn = getattr(arr, "is_ready", None)
    if fn is None:
        return True
    try:
        return bool(fn())
    except Exception:  # noqa: BLE001 — runtime quirk: assume complete
        return True


def _validate_chunks(dm, chunks, service: str, method: str) -> int:
    """Chunk admission, identical at every seam (proposer, accepting
    party's handler, the session runner): one copy so a future rule
    change can never let a proposal through one seam that another
    rejects.  Returns the normalized chunk count; raises ValueError
    (the handlers map it to a clean EREQUEST reject before lockstep)."""
    chunks = int(chunks or 1)
    if not (1 <= chunks <= MAX_CHUNKS):
        raise ValueError(f"chunks {chunks} outside 1..{MAX_CHUNKS}")
    if dm.width % chunks != 0:
        raise ValueError(
            f"chunks {chunks} does not divide method width {dm.width}"
        )
    if chunks > 1 and not getattr(dm, "chunkable", False):
        # chunk-safety is a registration-time declaration: a mismatch
        # must reject before lockstep, exactly like a fingerprint
        # mismatch — a silently mis-chunked kernel would diverge, not
        # fail
        raise ValueError(
            f"device method {service}.{method} is not registered "
            "chunkable (chunked overlap sessions need the chunk-safety "
            "declaration)"
        )
    align = int(getattr(dm, "chunk_align", 1) or 1)
    if chunks > 1 and (dm.width // chunks) % align != 0:
        # block-wise quantized kernels: a chunk cut mid-scale-block
        # would recompute block scales from partial blocks and diverge
        # from the full-width bytes — alignment is part of chunk-safety
        raise ValueError(
            f"chunk width {dm.width // chunks} is not a multiple of "
            f"{service}.{method}'s {align}-byte block alignment"
        )
    return chunks


def _validate_chunk_order(chunk_order, chunks: int) -> List[int]:
    """Session-uniform chunk dispatch order (the topology-aware
    scheduler's stamp): None is mesh order; anything else must be a
    permutation of the chunk set — every party dispatches the same
    sub-collective sequence or the chunk collectives cannot
    rendezvous.  Raises ValueError (handlers reject EREQUEST)."""
    if chunk_order is None:
        return list(range(chunks))
    order = [int(j) for j in chunk_order]
    if sorted(order) != list(range(chunks)):
        raise ValueError(
            f"chunk_order {order} is not a permutation of 0..{chunks - 1}"
        )
    return order


# -- topology-aware scheduling (TASP, PAPERS.md 2509.26541) --------------------
#
# The N-party fan-out and the chunk routes were dispatched in MESH order
# — blind to the fabric.  The DeviceLinkMap star has been measuring
# per-link rtt and bytes/s since PR 1; `link_profile()` (transport/
# device_link.py) snapshots those recorders, and the scheduler orders
# work by MEASURED speed instead: the slowest link's party is proposed
# to first — it needs the longest lead before every barrier (the TASP
# rule: schedule the scarce link before the fast ones).  The chunk
# dispatch order is derived from the same profile (see
# schedule_session_order for exactly what that does and does not buy —
# chunk sub-collectives are symmetric across links).  The chosen order
# and the profile it came from are stamped into the run proposal and
# the rpcz session span, so a surprising schedule is auditable after
# the fact.


def _profile_speed(info) -> Optional[tuple]:
    """Sort key for one party's measured link: (GB/s ascending, rtt
    DESCENDING) — slowest first; None when the link has no telemetry
    (no evidence of being slow: it keeps mesh order at the tail)."""
    if not info:
        return None
    gbps = float(info.get("gbps", 0.0) or 0.0)
    rtt = float(info.get("rtt_us", 0.0) or 0.0)
    if gbps <= 0.0 and rtt <= 0.0:
        return None
    return (gbps, -rtt)


def schedule_session_order(
    party_ids: List[int], profile, chunks: int = 1
) -> Tuple[List[int], List[int], str]:
    """The TASP join of a link profile and a session shape: returns
    (party_order, chunk_order, note).  ``party_order`` is every party
    index, measured links slowest-first, unmeasured parties trailing in
    mesh order — the load-bearing half: the fan-out RPC to the slowest
    link's party is issued first.  ``chunk_order`` is a deterministic
    dispatch permutation derived from the same measurements via a
    round-robin ROUTE LABEL (slice j labeled to party ``j % n``): chunk
    sub-collectives move EVERY party's slice, so no chunk belongs to a
    link — on XLA's symmetric lowering the order is latency-neutral,
    and its value is being a pure auditable function of the profile
    that fronts the slices labeled to slow parties on runtimes that do
    schedule sub-collective transfers in dispatch order.  Reordering
    never changes bytes (asserted by the overlap-composition tests).
    ``note`` is the audit string the rpcz span records.  With no
    measured link both orders degenerate to mesh order — the
    pre-topology behavior."""
    n = len(party_ids)
    profile = profile or {}
    measured, unmeasured = [], []
    for i, pid in enumerate(party_ids):
        key = _profile_speed(profile.get(int(pid)))
        if key is None:
            unmeasured.append(i)
        else:
            measured.append((key, i))
    measured.sort()
    party_order = [i for _k, i in measured] + unmeasured
    # rank only MEASURED parties: with an empty profile the chunk sort
    # key is (inf, j) everywhere and the order stays mesh
    rank = {i: pos for pos, (_k, i) in enumerate(measured)}
    chunk_order = sorted(
        range(int(chunks)),
        key=lambda j: (rank.get(j % n, float("inf")), j),
    )
    if measured:
        gbps = {
            int(party_ids[i]): round(
                float(profile[int(party_ids[i])].get("gbps", 0.0) or 0.0), 4
            )
            for _k, i in measured
        }
        note = f"link_order={party_order} profile_gbps={gbps}"
        if chunks > 1:
            note += f" chunk_order={chunk_order}"
    else:
        note = ""
    return party_order, chunk_order, note


def _default_link_profile():
    """The scheduler's default telemetry source: this process's live
    device-link star (best-effort — a process with no links schedules
    in mesh order)."""
    try:
        from incubator_brpc_tpu.transport.device_link import link_profile

        return link_profile()
    except Exception:  # noqa: BLE001 — scheduling is advisory, never fatal
        return {}


def run_dispatch_session(
    party_ids: List[int],
    own_index: int,
    dm,
    operands: List[bytes],
    steps: int,
    service: str = "?",
    method: str = "?",
    should_abort: Optional[Callable[[], Optional[str]]] = None,
    session_id: Optional[str] = None,
    resume_from: int = 0,
    resume_state: Optional[Dict[int, Tuple[bytes, int]]] = None,
    checkpoint_every: int = 0,
    step_deadline_ms: float = 0.0,
    session_epoch: int = 0,
    chunks: int = 1,
    double_buffer: bool = False,
    quantize: str = "none",
    chunk_order=None,
    trace_id: int = 0,
    parent_span_id: int = 0,
) -> Tuple[np.ndarray, int, float]:
    """Run this party's side of a K-step session of ``dm``'s kernel;
    returns (own final row, own final n, elapsed seconds). Every party
    calls this with identical arguments except ``own_index`` — the jitted
    programs must match or the collectives cannot rendezvous. Each party
    device-places the shards it can ADDRESS: in the multi-controller
    deployment that is exactly its own row (the peers' devices are
    visible but not addressable — they contribute their shards from their
    own processes); in a single-controller run one call owns every shard
    and the session degenerates to the full computation. Operands stay
    device-resident across the chain: only the initial device_put and the
    final fetch cross the host boundary, and XLA pipelines the K
    dispatches (the ack/credit discipline is the response barrier the
    proposer collects — no per-step coordination).

    Elastic extensions: with ``checkpoint_every`` > 0 (and a session id)
    every C-th completed step's global arrays are retained in this
    party's device-resident ring; ``resume_from`` = R restores step R's
    state — from the local ring when retained, else from
    ``resume_state`` ({slot: (full-width row bytes, n)}, the reshard a
    replacement party is bootstrapped with) — and replays only steps
    > R; ``step_deadline_ms`` arms a watchdog that aborts the session
    fabric-wide when a SINGLE step (or the final fetch) stalls, instead
    of waiting out the whole session deadline.

    Overlap extensions (T3, docs/DEVICE_PLANE.md "the overlap
    scheduler"): ``chunks=C`` splits every step's operand on its leading
    axis into C independently-dispatched sub-collectives (the kernel
    must be registered ``chunkable`` and C must divide the width); each
    chunk is acked independently (a non-blocking readiness probe riding
    the step-ack discipline) and stamps its OWN watchdog progress, so a
    long overlapped step is never falsely aborted and an abort reason
    names step+chunk.  ``double_buffer=True`` keeps two step slots in
    flight: the ack of step k's chunk j is what (at the dataflow level)
    triggers step k+1's slice j — the host never blocks (zero host sync
    on the hot path; the device orders the chunk chain by dependency),
    whereas ``double_buffer=False`` with chunks inserts the serialized
    step-granularity ack barrier the A/B bench compares against.
    Checkpoints always capture WHOLE steps (the chunk slices re-concat
    before entering the ring), so a resume point is never a torn chunk.
    ``chunks=1, double_buffer=False`` is exactly the pre-overlap code
    path.

    Quantized extensions (parallel/quantized.py): ``quantize`` selects
    the kernel variant this chain binds — "none" runs ``dm`` itself,
    "int8"/"int4" resolve ``dm.quantized(mode)`` (no variant = clean
    ValueError before any dispatch); a quantized session also stores its
    checkpoint ring entries in the QUANTIZED representation (same ~4x as
    the wire), and the power-of-two scale discipline keeps resume replay
    byte-identical.  ``chunk_order`` is the topology-aware scheduler's
    session-uniform dispatch permutation over the chunk set (None = mesh
    order); chunk sub-collectives are independent, so the order never
    changes bytes — only which slice fronts the schedule."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    qdm = dm.quantized(quantize) if hasattr(dm, "quantized") else dm
    if qdm is None:
        raise ValueError(
            f"device method {service}.{method} has no {quantize} "
            "quantized variant"
        )
    dm = qdm
    quant_mode = getattr(dm, "quant_mode", "none") or "none"
    devices = _devices_by_id(party_ids)
    n = len(devices)
    if len(operands) != n:
        raise ValueError("one operand per party required")
    if not (0 <= resume_from <= steps):
        raise ValueError(f"resume_from {resume_from} outside 0..{steps}")
    chunks = _validate_chunks(dm, chunks, service, method)
    chunk_order = _validate_chunk_order(chunk_order, chunks)
    chunked = chunks > 1 or double_buffer
    mesh = Mesh(np.asarray(devices), ("par",))
    sharding = NamedSharding(mesh, P("par"))
    step_fn = _make_step(dm, mesh, sharding, party_ids)

    addressable = sharding.addressable_devices
    own_dev = devices[own_index]
    if own_dev not in addressable:
        raise ValueError(
            f"party {own_index} device {own_dev} is not addressable from "
            f"this process"
        )
    ring = None
    if checkpoint_every and checkpoint_every > 0 and session_id:
        n_addr = sum(1 for d in devices if d in addressable)
        # a quantized session retains QUANTIZED ring entries: the per-
        # entry device cost drops from width float32-bytes per row to
        # the wire footprint — deep rings get the same ~4x the wire got
        row_cost = dm.wire_bytes() if quant_mode != "none" else dm.width
        ring = _checkpoint_ring(
            session_id, own_index, party_ids,
            entry_bytes=n_addr * (row_cost + 4),
        )
    ck_qz = None
    if ring is not None and quant_mode != "none":
        ck_qz = _make_ck_quant(mesh, sharding, dm, party_ids)

    def _ck_payload(rows):
        """What enters the ring: the raw row array, or (quantized
        session) its block-quantized twin — dispatched async like the
        chain itself, no host sync here either."""
        if ck_qz is None:
            return rows
        q_arr, e_arr = ck_qz(rows)
        return _QuantCk(q_arr, e_arr, quant_mode, dm.quant_block, dm.width)
    restored = None
    if resume_from > 0:
        restored = _restore_state(
            session_id, own_index, resume_from, devices, addressable,
            dm, resume_state,
        )
        if restored is None:
            raise LookupError(
                f"no checkpoint for session {session_id} step "
                f"{resume_from} reachable from party {own_index}"
            )
    row_shards, n_shards = [], []
    if restored is None:
        for i, dev in enumerate(devices):
            if dev not in addressable:
                continue
            row, nn = dm.pack(operands[i])
            row_shards.append(jax.device_put(row[None, :], dev))
            n_shards.append(
                jax.device_put(np.asarray([nn], dtype=np.int32), dev)
            )
    else:
        row_shards, n_shards = restored
    x = jax.make_array_from_single_device_arrays(
        (n, dm.width), sharding, row_shards
    )
    ns = jax.make_array_from_single_device_arrays((n,), sharding, n_shards)

    # the per-step watchdog: ``progress`` is (step index, last progress
    # instant, chunk index), advanced by the chain before every dispatch
    # and before the final fetch; a stall past the step deadline aborts
    # the session FABRIC-WIDE (abort_session → every local registrant's
    # event + the proposer's watcher sees the ESESSION answers), so one
    # wedged step costs the fabric a step deadline, not a session
    # deadline.  The wedged party itself still finishes its blocking
    # device call first — what the watchdog bounds is how long everyone
    # ELSE waits.  A CHUNKED step is C progress stamps, not one: each
    # sub-collective advances the stamp, so a long overlapped step is
    # never falsely aborted, a stall is attributed to step+chunk, and
    # with double-buffering a stalled last chunk of step k is named as
    # step k's — not misread as step k+1 hanging.
    # Dispatches are ASYNC (the host loop stamps per-step progress while
    # XLA pipelines the compute), so the final fetch is where the whole
    # replayed chain's device time is actually awaited: its allowance is
    # one step deadline PER replayed step, not one — a healthy long
    # session must not be aborted for merely computing.
    wd_stop = None
    progress = [resume_from, time.monotonic(), -1]
    # per-slice ack watermark: acked[j] = lowest step whose chunk-j ack
    # has NOT been observed yet; the fetch-phase abort reason names the
    # oldest unacked (step, chunk) so a wedged sub-collective is
    # attributed, not just "the fetch is slow"
    acked = [resume_from] * chunks
    if step_deadline_ms and step_deadline_ms > 0 and session_id:
        wd_stop = threading.Event()
        budget_s = step_deadline_ms / 1000.0
        fetch_allow_s = budget_s * max(1, steps - resume_from)

        def _watch_steps(sid=session_id, ep=session_epoch):
            poll = min(0.01, budget_s / 4)
            while not wd_stop.wait(poll):
                allowed = budget_s if progress[0] < steps else fetch_allow_s
                if time.monotonic() - progress[1] > allowed:
                    if progress[0] < steps:
                        what = f"step {progress[0]}"
                        if progress[2] >= 0:
                            what += f" chunk {progress[2]}/{chunks}"
                    else:
                        what = "final fetch"
                        oldest = min(acked)
                        if chunked and oldest < steps:
                            what += (
                                f" (oldest unacked step {oldest} chunk "
                                f"{acked.index(oldest)}/{chunks})"
                            )
                    abort_session(
                        sid,
                        f"{what} exceeded the {step_deadline_ms:g}ms "
                        "step deadline",
                        epoch=ep,
                    )
                    return

        threading.Thread(
            target=_watch_steps, name="mc-step-watchdog", daemon=True
        ).start()
    t0 = time.perf_counter()
    chunk_tally = 0  # sub-collectives dispatched (folded into bvars once)
    overlap_tally = 0  # dispatched while the same slice's predecessor flew
    pending_spans: List[list] = [[] for _ in range(chunks)]
    step_span = None
    try:
        if not chunked:
            for step_i in range(resume_from, steps):
                # fault plane: an aborted session exits the chain HERE,
                # between dispatches, with a clean ESESSION — dispatches
                # are async (XLA pipelines them), so the check costs
                # nothing and the party never enters a barrier its dead
                # peer cannot join.  A party already blocked INSIDE one
                # collective finishes that step first (or hits the
                # runtime's own collective timeout) — the between-step
                # check, every party's deadline watch, and the per-step
                # watchdog are what bound the hang.
                if should_abort is not None:
                    why = should_abort()
                    if why:
                        raise SessionAborted(why)
                progress[0], progress[1] = step_i, time.monotonic()
                hook = _step_hook
                if hook is not None:
                    hook(step_i, own_index, 0)  # chaos-drill seam
                x, ns = step_fn(x, ns)  # chained: operands stay on-device
                completed = step_i + 1
                if ring is not None and completed % checkpoint_every == 0:
                    # retaining the global arrays IS the checkpoint: the
                    # buffers stay device-resident, no host sync happens
                    # here, and the ring caps how many stay alive
                    ring.put(
                        completed, _ck_payload(x), ns,
                        int(get_flag("mc_dispatch_checkpoint_depth")),
                    )
        else:
            # -- the overlap scheduler: chunked sub-collectives ---------
            # the chunk sub-collective is step_fn itself applied to a
            # slice (jit re-specializes per shape; chunk-safety makes
            # the slice's bytes the slice of the full-width bytes)
            chunk_fn = step_fn
            concat_fn = None
            if chunks > 1:
                split_fn, concat_fn = _make_chunk_ops(
                    mesh, sharding, dm.width, chunks, party_ids
                )
                xs = list(split_fn(x))
            else:
                xs = [x]
            for step_i in range(resume_from, steps):
                step_span = _start_step_span(
                    service, method, step_i, steps, chunks, double_buffer,
                    trace_id, parent_span_id,
                )
                # chunk_order: the stamped topology-derived dispatch
                # permutation (independent sub-collectives: order
                # changes dispatch sequence, never bytes — see
                # schedule_session_order for its exact semantics)
                for j in chunk_order:
                    # the fault plane extends per-chunk: an abort lands
                    # BETWEEN sub-collectives, and the torn step (some
                    # chunks dispatched, others not) never checkpoints —
                    # a resume point is always a whole-step boundary
                    if should_abort is not None:
                        why = should_abort()
                        if why:
                            raise SessionAborted(why)
                    progress[0], progress[2] = step_i, j
                    progress[1] = time.monotonic()
                    hook = _step_hook
                    if hook is not None:
                        hook(step_i, own_index, j)  # chaos-drill seam
                    if double_buffer and step_i > resume_from:
                        # chunk-ack observation riding the step-ack
                        # discipline: xs[j] IS step k-1's chunk-j
                        # output.  Ready → the ack is observed (spans
                        # close, the watermark advances).  Not ready →
                        # the predecessor sub-collective is still in
                        # flight while the next slice dispatches: that
                        # IS the overlap, tallied.  Never blocks — the
                        # device orders the chain by dataflow, so the
                        # ack-gates-dispatch discipline holds on-device
                        # with zero host sync added.
                        if _chunk_ready(xs[j]):
                            acked[j] = step_i
                            _close_spans(pending_spans[j])
                        else:
                            overlap_tally += 1
                    # ns is NOT rethreaded through the chunk programs:
                    # the chunkable contract passes n through unchanged,
                    # so consuming a chunk's m output would only hand
                    # every slice of step k+1 a dataflow edge on step
                    # k's chunk-0 program — partially re-serializing the
                    # overlap the schedule exists to remove
                    new_x, _ = chunk_fn(xs[j], ns)
                    xs[j] = new_x
                    chunk_tally += 1
                    csp = _start_chunk_span(
                        service, method, step_i, j, chunks, step_span,
                        trace_id, parent_span_id,
                    )
                    if csp is not None:
                        pending_spans[j].append(csp)
                completed = step_i + 1
                if not double_buffer:
                    # serialized schedule: the step-granularity ack
                    # barrier the overlap replaces — every chunk of this
                    # step observed complete before the next dispatches
                    # (the A/B baseline; a stalled chunk is named by its
                    # own progress stamp)
                    for j in chunk_order:
                        progress[0], progress[2] = step_i, j
                        progress[1] = time.monotonic()
                        jax.block_until_ready(xs[j])
                        acked[j] = completed
                        _close_spans(pending_spans[j])
                if ring is not None and completed % checkpoint_every == 0:
                    # whole-step checkpoint: the chunk slices re-concat
                    # (async, device-resident) before entering the ring
                    # — a torn chunk can never become a resume point
                    x_ck = concat_fn(*xs) if chunks > 1 else xs[0]
                    ring.put(
                        completed, _ck_payload(x_ck), ns,
                        int(get_flag("mc_dispatch_checkpoint_depth")),
                    )
                if step_span is not None:
                    _end_session_span(step_span)
                    step_span = None
            x = concat_fn(*xs) if chunks > 1 else xs[0]
        if should_abort is not None:
            # last look before the blocking fetch: the final collect is
            # the one host-blocking point of the chain
            why = should_abort()
            if why:
                raise SessionAborted(why)
        progress[0], progress[2] = steps, -1
        progress[1] = time.monotonic()
        own_row = own_n = None
        for s in x.addressable_shards:
            # a process can address several mesh devices (single-
            # controller runs): OUR shard is the one on devices[own_index]
            if s.device == own_dev:
                own_row = np.asarray(s.data).reshape(-1)
        for s in ns.addressable_shards:
            if s.device == own_dev:
                own_n = int(np.asarray(s.data).reshape(-1)[0])
        # the fetch materialized the whole chain: every outstanding chunk
        # ack is implied — close the remaining spans at their true ack
        # instant and settle the watermark
        for j in range(chunks):
            acked[j] = steps
            _close_spans(pending_spans[j])
    finally:
        if wd_stop is not None:
            wd_stop.set()
        if chunk_tally:
            dispatch_chunks << chunk_tally
        if overlap_tally:
            dispatch_overlapped_chunks << overlap_tally
        # an abort mid-step leaves spans open: close them as errored so
        # the trace shows the torn step instead of losing it
        from incubator_brpc_tpu.utils.status import ErrorCode as _EC

        for j in range(chunks):
            _close_spans(pending_spans[j], error_code=int(_EC.ESESSION))
        if step_span is not None:
            _end_session_span(step_span, error_code=int(_EC.ESESSION))
    elapsed = time.perf_counter() - t0
    assert own_row is not None and own_n is not None
    dispatch_sessions << 1
    dispatch_steps << (steps - resume_from)
    dispatch_session_us << elapsed * 1e6
    _method_counter(service, method) << 1
    if quant_mode != "none":
        # the quantization dividend, tallied once per session: bytes
        # the wire did NOT carry vs the exact float32 row at this
        # width, across every party and replayed step
        dispatch_quantized_sessions << 1
        saved = (dm.width - dm.wire_bytes()) * n * (steps - resume_from)
        if saved > 0:
            dispatch_bytes_saved << saved
    return own_row, own_n, elapsed


def _restore_state(
    session_id, own_index, step, devices, addressable, dm, resume_state
):
    """Rebuild this party's addressable shards of the session state at
    one checkpointed step: the local ring's device-resident buffers when
    retained (a survivor resuming in place — same devices, zero copies),
    falling back per-slot to ``resume_state`` rows shipped over the rpc
    plane (the replacement's bootstrap; also covers a survivor whose ring
    lost the slot).  Returns (row_shards, n_shards) or None when any
    addressable slot is unrecoverable."""
    import jax

    ring = _checkpoint_lookup(session_id, own_index) if session_id else None
    entry = ring.get(int(step)) if ring is not None else None
    payload, by_dev_n, old_pids = None, {}, ()
    if entry is not None:
        payload, old_ns = entry
        by_dev_n = {s.device: s for s in old_ns.addressable_shards}
        old_pids = ring.party_ids
    pay_devs = (
        [s.device for s in _payload_arrays(payload)[0].addressable_shards]
        if payload is not None
        else []
    )
    state = resume_state or {}
    row_shards, n_shards = [], []
    for i, dev in enumerate(devices):
        if dev not in addressable:
            continue
        src_dev = None
        if i < len(old_pids):
            src = [d for d in pay_devs if d.id == old_pids[i]]
            src_dev = src[0] if src else None
        if src_dev is not None and src_dev in by_dev_n:
            n_buf = by_dev_n[src_dev].data
            if isinstance(payload, _QuantCk):
                # quantized ring: the retained entry is the block-
                # quantized representation — dequantize on the host
                # (exact, power-of-two scales) and re-place.  The first
                # replayed step re-quantizes to the identical wire
                # bytes (idempotence), so the chain stays byte-
                # identical to the undisturbed run.
                row = payload.shard_row(src_dev)
                if row is None:
                    return None
                row_shards.append(jax.device_put(row.reshape(1, -1), dev))
                n_shards.append(jax.device_put(np.asarray(n_buf), dev))
                continue
            by_dev_row = {s.device: s for s in payload.addressable_shards}
            row_buf = by_dev_row[src_dev].data
            if src_dev != dev:
                # a replaced slot restored from a survivor's ring: the
                # retained buffer lives on the OLD device — move it
                row_buf = jax.device_put(np.asarray(row_buf), dev)
                n_buf = jax.device_put(np.asarray(n_buf), dev)
            row_shards.append(row_buf)
            n_shards.append(n_buf)
            continue
        if int(i) in state:
            row_bytes, nn = state[int(i)]
            try:
                row, n32 = dm.pack_state(row_bytes, nn)
            except ValueError:
                return None  # wrong-geometry reshard: unrecoverable slot
            row_shards.append(jax.device_put(row[None, :], dev))
            n_shards.append(
                jax.device_put(np.asarray([n32], dtype=np.int32), dev)
            )
            continue
        return None
    return row_shards, n_shards


# -- rpcz spans (annotated with method identity) -------------------------------


def _start_session_span(
    service: str,
    method: str,
    fingerprint: str,
    party_ids: List[int],
    own_index: int,
    steps: int,
    trace_id: int = 0,
    parent_span_id: int = 0,
    resume_from: int = 0,
    extra: str = "",
    forced: bool = False,
):
    from incubator_brpc_tpu.builtin.rpcz import (
        SPAN_TYPE_COLLECTIVE,
        start_custom_span,
    )

    span = start_custom_span(
        SPAN_TYPE_COLLECTIVE,
        service,
        method,
        trace_id=trace_id,
        parent_span_id=parent_span_id,
        forced=forced,
    )
    if span is not None:
        note = (
            f"method={service}.{method} fingerprint={fingerprint} "
            f"steps={steps} index={own_index} parties={party_ids}"
        )
        if resume_from > 0:
            # a resumed chain: the span shows how much work the
            # checkpoint saved (only steps > resume_from re-ran)
            note += f" resume_from={resume_from}"
        if extra:
            # quantize= / link-order audit trail (docs/OBSERVABILITY.md)
            note += " " + extra
        span.annotate(note)
    return span


def _end_session_span(span, error_code: int = 0) -> None:
    from incubator_brpc_tpu.builtin.rpcz import end_custom_span

    end_custom_span(span, error_code=error_code)


def _start_step_span(
    service, method, step_i, steps, chunks, double_buffer,
    trace_id, parent_span_id,
):
    """One step's COMPUTE span in an overlapped session: covers the host
    dispatch window of the step's sub-collectives; its children are the
    chunk spans, and a chunk span of step k that closes inside step
    k+1's window is the trace-level proof of overlap."""
    from incubator_brpc_tpu.builtin.rpcz import (
        SPAN_TYPE_COLLECTIVE,
        start_custom_span,
    )

    span = start_custom_span(
        SPAN_TYPE_COLLECTIVE, service, method,
        trace_id=trace_id, parent_span_id=parent_span_id,
    )
    if span is not None:
        span.annotate(
            f"compute step={step_i}/{steps} chunks={chunks} "
            f"schedule={'double_buffer' if double_buffer else 'serialized'}"
        )
    return span


def _start_chunk_span(
    service, method, step_i, j, chunks, step_span, trace_id, parent_span_id
):
    """One chunk sub-collective's span, nested inside its step's compute
    span (``chunk=<j>/<C>`` annotation schema, docs/OBSERVABILITY.md);
    ended at the chunk's ACK observation, so its interval is
    dispatch→ack — time-overlapping the next slice's compute span when
    the schedule actually overlaps."""
    from incubator_brpc_tpu.builtin.rpcz import (
        SPAN_TYPE_COLLECTIVE,
        start_custom_span,
    )

    span = start_custom_span(
        SPAN_TYPE_COLLECTIVE, service, method,
        trace_id=step_span.trace_id if step_span is not None else trace_id,
        parent_span_id=(
            step_span.span_id if step_span is not None else parent_span_id
        ),
    )
    if span is not None:
        span.annotate(f"chunk={j}/{chunks} step={step_i}")
    return span


def _close_spans(spans: list, error_code: int = 0) -> None:
    """End-and-drain a slice's pending chunk spans (ack observed, or the
    session tore down) — draining keeps a second close idempotent."""
    while spans:
        _end_session_span(spans.pop(0), error_code=error_code)


# -- server half ---------------------------------------------------------------


def _validate_proposal(req: dict):
    """Shared accept/run admission: bounds, then kernel identity. Returns
    (party_ids, own_index, steps, dm, err) where err is (code, text) on
    rejection — the clean control-stream reject that keeps a divergent
    party out of lockstep."""
    from incubator_brpc_tpu.utils.status import ErrorCode

    try:
        party_ids = [int(i) for i in req["parties"]]
        own_index = int(req["index"])
        steps = int(req["steps"])
        width = int(req["width"])
        service = str(req["service"])
        method = str(req["method"])
        fingerprint = str(req["fingerprint"])
        quantize = str(req.get("quantize", "") or "none")
    except (ValueError, KeyError, TypeError) as e:
        return None, None, None, None, (
            ErrorCode.EREQUEST, f"bad dispatch proposal: {e}"
        )
    if not (
        0 < steps <= MAX_STEPS
        and 0 < width <= MAX_WIDTH
        and 1 < len(party_ids) <= MAX_PARTIES
        and 0 <= own_index < len(party_ids)
        and len(set(party_ids)) == len(party_ids)
    ):
        return None, None, None, None, (
            ErrorCode.EREQUEST, "dispatch proposal out of bounds"
        )
    from incubator_brpc_tpu.parallel.quantized import QUANT_MODES

    if quantize not in QUANT_MODES:
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.EREQUEST, f"unknown quantize mode {quantize!r}"
        )
    dm = resolve_method(service, method, width)
    if dm is None:
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.ENOMETHOD,
            f"no device method {service}.{method} with width {width} "
            f"registered in this process",
        )
    dm = dm.quantized(quantize)
    if dm is None:
        # the session is quantized but this method registered no such
        # variant here — same class of divergence as a fingerprint
        # mismatch, same clean pre-lockstep reject
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.EREQUEST,
            f"device method {service}.{method} has no {quantize} "
            f"quantized variant registered in this process",
        )
    ours = dm.fingerprint()
    if ours != fingerprint:
        # same name, different kernel: entering lockstep would run a
        # program the proposer never named — reject before any dispatch
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.EREQUEST,
            f"device method fingerprint mismatch for {service}.{method}: "
            f"proposal {fingerprint} vs local {ours}",
        )
    try:
        _devices_by_id(party_ids)
    except ValueError as e:
        return None, None, None, None, (ErrorCode.EREQUEST, str(e))
    return party_ids, own_index, steps, dm, None


def make_dispatch_handler(server):
    """Server half of ``_tpu_transport.collective_dispatch``: validate a
    session proposal against the local registry (accept phase — nothing
    runs), or bind the resolved kernel and run this party's side of the
    lockstep chain (run phase), answering with the final shard."""

    def collective_dispatch(cntl, request: bytes) -> bytes:
        try:
            req = json.loads(request.decode())
        except ValueError as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"undecodable proposal: {e}")
            return b""
        if req.get("phase") == "abort":
            # the abort broadcast: validated as little as possible — a
            # survivor must unwedge even when the rest of the proposal
            # state is unreachable or corrupt
            sid = str(req.get("session_id", ""))
            try:
                # epoch-scoped: a straggler abort from a superseded run
                # must not kill the session's resumed run
                abort_epoch = (
                    int(req["epoch"]) if "epoch" in req else None
                )
            except (ValueError, TypeError):
                abort_epoch = None
            found = bool(sid) and abort_session(
                sid,
                str(req.get("reason", "")) or "aborted by proposer",
                epoch=abort_epoch,
            )
            return json.dumps({"aborted": found}).encode()
        if req.get("phase") == "resume_query":
            # the resume barrier's census: every LOCAL party's checkpoint
            # watermark for this session — the proposer min-joins these
            # over the survivors into the resume point
            sid = str(req.get("session_id", ""))
            wm = checkpoint_watermarks(sid) if sid else {}
            return json.dumps(
                {"watermarks": {str(k): v for k, v in wm.items()}}
            ).encode()
        if req.get("phase") == "fetch_shard":
            # reshard: materialize checkpointed rows for the requested
            # slots (the replacement party's bootstrap state)
            sid = str(req.get("session_id", ""))
            step = int(req.get("step", 0) or 0)
            slots = [int(s) for s in req.get("slots", ())]
            rows = checkpoint_fetch(sid, step, slots) if sid else {}
            return json.dumps(
                {"rows": {str(k): v for k, v in rows.items()}}
            ).encode()
        if req.get("phase") == "release":
            sid = str(req.get("session_id", ""))
            return json.dumps(
                {"released": bool(sid) and release_checkpoints(sid)}
            ).encode()
        party_ids, own_index, steps, dm, err = _validate_proposal(req)
        if err is not None:
            cntl.set_failed(*err)
            return b""
        service, method = str(req["service"]), str(req["method"])
        floor = int(get_flag("mc_dispatch_min_steps"))
        if req.get("phase") != "accept" and steps < floor:
            # the accept ack raised our target to the floor; a run
            # proposal below it means the proposer did not fold this
            # party's target — reject rather than silently dispatch a
            # count the accept never agreed to (the close-barrier echo
            # below only proves the VALIDATED count was run)
            from incubator_brpc_tpu.utils.status import ErrorCode

            dispatch_rejects << 1
            cntl.set_failed(
                ErrorCode.EREQUEST,
                f"run proposal steps {steps} below this party's accepted "
                f"floor {floor}",
            )
            return b""
        if req.get("phase") == "accept":
            # Nothing is run or reserved; ``target`` lets this party RAISE
            # the step count (mc_dispatch_min_steps — e.g. a pipeline-depth
            # floor). The proposer folds every target with max — the
            # 2-party close dance's max(targets) join, generalized to N.
            target = min(
                max(steps, int(get_flag("mc_dispatch_min_steps"))), MAX_STEPS
            )
            return json.dumps(
                {"accept": True, "index": own_index, "target": target}
            ).encode()
        try:
            operands = [
                base64.b64decode(op) for op in req.get("operands", [])
            ]
            if len(operands) != len(party_ids):
                raise ValueError("one operand per party required")
            for op in operands:
                if len(op) > dm.width:
                    raise ValueError(
                        f"operand of {len(op)}B exceeds width {dm.width}"
                    )
        except (ValueError, TypeError) as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"bad operands: {e}")
            return b""
        # fault plane: a session_id-carrying run registers here so the
        # abort broadcast, the party's own deadline watch, link-death
        # feedback, and the proposer's control socket dying can all
        # unwedge this party mid-chain with a clean ESESSION
        session_id = str(req.get("session_id", "")) or None
        # elastic plane: the proposer stamps the checkpoint cadence and
        # step deadline into the run proposal (cadence MUST be uniform
        # across parties or the min-join loses its "last common step"
        # meaning); absent fields fall back to this party's own flags
        try:
            run_epoch = int(req.get("epoch", 0) or 0)
            resume_from = int(req.get("resume_from", 0) or 0)
            # overlap fields: the proposer stamps the chunk count and
            # schedule into the run proposal (session-uniform — every
            # party must dispatch the same sub-collective sequence or
            # the chunk collectives cannot rendezvous)
            chunks = _validate_chunks(
                dm, req.get("chunks", 1), service, method
            )
            chunk_order = _validate_chunk_order(
                req.get("chunk_order"), chunks
            )
            double_buffer = bool(req.get("double_buffer", False))
            if "checkpoint_every" in req:
                checkpoint_every = int(req["checkpoint_every"] or 0)
            else:
                checkpoint_every = int(get_flag("mc_dispatch_checkpoint_every"))
            if "step_deadline_ms" in req:
                step_deadline_ms = float(req["step_deadline_ms"] or 0)
            else:
                step_deadline_ms = float(
                    get_flag("mc_dispatch_step_deadline_ms")
                )
            resume_state = {
                int(k): (base64.b64decode(v["row"]), int(v["n"]))
                for k, v in (req.get("resume_state") or {}).items()
            }
            if not (0 <= resume_from <= steps):
                raise ValueError(f"resume_from {resume_from} out of bounds")
            if resume_from > 0 and session_id is None:
                raise ValueError("resume_from requires a session_id")
        except (ValueError, TypeError, KeyError) as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            dispatch_rejects << 1
            cntl.set_failed(ErrorCode.EREQUEST, f"bad run fields: {e}")
            return b""
        st = None
        sock_hook = None
        if session_id is not None and run_epoch <= aborted_epoch(session_id):
            # the abort for this epoch already passed through here: a
            # stale (reordered or retried) run proposal must not start a
            # zombie chain no peer will ever join
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(
                ErrorCode.ESESSION,
                f"session aborted: run epoch {run_epoch} already "
                "tombstoned on this party",
            )
            return b""
        if session_id is not None:
            deadline_ms = float(req.get("deadline_ms", 0) or 0)
            if deadline_ms <= 0:
                deadline_ms = float(get_flag("mc_dispatch_session_deadline_ms"))
            deadline = (
                time.monotonic() + deadline_ms / 1000.0 if deadline_ms > 0
                else 0.0
            )
            st = _register_session(
                session_id, party_ids, deadline, owner=server,
                epoch=run_epoch,
            )
            sock = getattr(cntl, "_sock", None)
            hooks = getattr(sock, "on_failed", None)
            if hooks is not None:
                # the proposer died with us mid-chain: its control
                # connection failing IS the death signal (socket feedback)
                def _proposer_died(_s, _sid=session_id, _ep=run_epoch):
                    abort_session(
                        _sid, "proposer connection died mid-session",
                        epoch=_ep,
                    )

                hooks.append(_proposer_died)
                sock_hook = (hooks, _proposer_died)

        def _should_abort():
            if st is None:
                return None
            if st.abort_event.is_set():
                return st.abort_reason or "session aborted"
            if st.deadline and time.monotonic() > st.deadline:
                abort_session(
                    st.session_id, "session deadline exceeded",
                    epoch=st.epoch,
                )
                return "session deadline exceeded"
            return None

        quant_note = ""
        if getattr(dm, "quant_mode", "none") != "none":
            quant_note = f"quantize={dm.quant_mode}"
        if chunk_order != list(range(chunks)):
            # the proposer's topology-derived route, auditable per party
            quant_note = (
                quant_note + f" chunk_order={chunk_order}"
            ).strip()
        span = _start_session_span(
            service, method, dm.fingerprint(), party_ids, own_index, steps,
            trace_id=cntl.trace_id, parent_span_id=cntl.span_id,
            resume_from=resume_from, extra=quant_note,
            # the proposal rode in sampled (head-based): this party's
            # session span must not drop to a dry local bucket, or the
            # fleet-wide trace loses a whole party
            forced=bool(
                getattr(cntl.request_meta, "sampled", 0)
                if cntl.request_meta is not None
                else 0
            ),
        )
        try:
            own_row, own_n, elapsed = run_dispatch_session(
                party_ids, own_index, dm, operands, steps,
                service=service, method=method, should_abort=_should_abort,
                session_id=session_id, resume_from=resume_from,
                resume_state=resume_state,
                checkpoint_every=checkpoint_every,
                step_deadline_ms=step_deadline_ms,
                session_epoch=run_epoch,
                chunks=chunks, double_buffer=double_buffer,
                chunk_order=chunk_order,
                # step/chunk spans nest inside the session span (or the
                # proposing RPC's trace when the session span was not
                # sampled this time)
                trace_id=(
                    span.trace_id if span is not None else cntl.trace_id
                ),
                parent_span_id=(
                    span.span_id if span is not None else cntl.span_id
                ),
            )
        except SessionAborted as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            _end_session_span(span, error_code=ErrorCode.ESESSION)
            cntl.set_failed(ErrorCode.ESESSION, f"session aborted: {e.reason}")
            return b""
        except LookupError as e:
            # a resume proposal for a step this party no longer retains
            # (evicted ring, wrong process): a clean control-stream
            # reject — the proposer falls back to a full restart
            from incubator_brpc_tpu.utils.status import ErrorCode

            dispatch_rejects << 1
            _end_session_span(span, error_code=ErrorCode.EREQUEST)
            cntl.set_failed(ErrorCode.EREQUEST, f"cannot resume: {e}")
            return b""
        except Exception as e:
            dispatch_errors << 1
            from incubator_brpc_tpu.utils.status import ErrorCode

            _end_session_span(span, error_code=ErrorCode.EINTERNAL)
            logger.exception("dispatch session failed")
            cntl.set_failed(ErrorCode.EINTERNAL, f"dispatch session: {e!r}")
            return b""
        finally:
            if sock_hook is not None:
                try:
                    sock_hook[0].remove(sock_hook[1])
                except ValueError:
                    pass
            if st is not None:
                _unregister_session(st)
        _end_session_span(span)
        return json.dumps(
            {
                "result": base64.b64encode(
                    dm.unpack(own_row, own_n)
                ).decode(),
                "steps": steps,
                "resumed_from": resume_from,
                "elapsed_s": elapsed,
                "index": own_index,
            }
        ).encode()

    return collective_dispatch


# -- client half: the N-party session scheduler --------------------------------


def propose_dispatch(
    channels,
    party_ids: List[int],
    service: str,
    method: str,
    operands: List[bytes],
    steps: int = 1,
    proposer_index: Optional[int] = None,
    timeout_ms: float = 120000,
    session_deadline_ms: Optional[float] = None,
    session_id: Optional[str] = None,
    resume_from: int = 0,
    resume_state: Optional[Dict[int, Tuple[bytes, int]]] = None,
    resume_state_slots=None,
    checkpoint_every: Optional[int] = None,
    step_deadline_ms: Optional[float] = None,
    epoch: int = 0,
    chunks: int = 1,
    double_buffer: bool = False,
    quantize: str = "none",
    link_profile=None,
    chunk_order=None,
) -> dict:
    """Schedule an N-party session of a registered device method.

    ``chunks``/``double_buffer`` select the overlap schedule (T3): every
    step's operand splits into ``chunks`` independently-acked
    sub-collectives, and with ``double_buffer`` two step slots stay in
    flight (see :func:`run_dispatch_session`).  The proposer stamps both
    into the run proposal — the schedule is session-uniform, like the
    checkpoint cadence — and validates chunk-safety against its own
    registry before the accept fan-out.

    ``quantize`` ("none"/"int8"/"int4") binds the session to the named
    method's QUANTIZED variant (parallel/quantized.py): the proposal
    stamps the mode and the VARIANT's fingerprint, every party resolves
    the same variant locally and fingerprint-validates it at accept —
    exact vs quantized can never silently mix in one lockstep chain.
    A method with no such variant rejects cleanly before any fan-out.

    Topology awareness (TASP): the accept and run fan-outs are issued
    slowest-measured-link FIRST, and with ``chunks > 1`` the stamped
    ``chunk_order`` front-loads the slices owned by the slowest parties
    — both derived from ``link_profile`` ({device id: {"gbps",
    "rtt_us", ...}}, default this process's live DeviceLinkMap snapshot)
    and recorded in the rpcz session span so the chosen order is
    auditable.  Pass ``chunk_order`` explicitly to override the derived
    route (it must be a permutation of the chunk set).

    ``party_ids`` are global device ids in mesh order; ``operands[i]`` is
    party i's initial row. ``channels[j]`` is a host channel to the
    server playing the j-th REMOTE party index (every index except
    ``proposer_index``; with ``proposer_index=None`` the proposer is a
    pure scheduler and every party is remote — the ParallelChannel
    lowering's shape). Returns ``{"results": [bytes per party],
    "final_steps": k, "elapsed_s": proposer's chain seconds or None}``.

    Three phases over the star:
    1. accept fan-out + barrier — every party resolves the (service,
       method) pair locally and fingerprint-checks it; any reject
       surfaces HERE, before lockstep. ``final = max(all targets)``.
    2. run fan-out (async — every party must be dispatching before any
       can finish) under a fault watcher, then the proposer's own chain
       if it participates.
    3. completion barrier — every response must echo ``final`` (the
       convergent close: all parties dispatched exactly the same count).

    Fault semantics: the run phase registers a SESSION (random id +
    ``session_deadline_ms`` budget, default the RPC timeout) on every
    party.  The watcher classifies a failed run RPC: connectivity
    failures (dead party) and rejects both trigger an ABORT — an abort
    broadcast to every surviving party plus the local abort event — so
    every survivor exits its lockstep chain with ESESSION instead of
    hanging in a barrier; :class:`SessionAborted` then carries the dead
    and surviving index sets for the re-propose path
    (:func:`propose_with_recovery`).  Breaker feedback is charged to the
    dead party only: the survivors' ESESSION answers are excluded from
    error cost by the LB (lb/__init__._feed_breaker).
    """
    import threading as _threading

    n = len(party_ids)
    remote_indexes = [i for i in range(n) if i != proposer_index]
    if len(remote_indexes) != len(channels):
        raise ValueError("one channel per remote party required")
    if len(operands) != n:
        raise ValueError("one operand per party required")
    dm = resolve_method(service, method)
    if dm is None:
        raise LookupError(
            f"device method {service}.{method} not registered locally "
            f"(the proposer validates against its own registry too)"
        )
    quantize = (quantize or "none").strip() or "none"
    qdm = dm.quantized(quantize)
    if qdm is None:
        raise LookupError(
            f"device method {service}.{method} has no {quantize} "
            "quantized variant registered locally"
        )
    dm = qdm
    fingerprint = dm.fingerprint()
    for op in operands:
        if len(op) > dm.width:
            raise ValueError(
                f"operand of {len(op)}B exceeds method width {dm.width}"
            )
    chunks = _validate_chunks(dm, chunks, service, method)
    # topology-aware route: fan out slowest-measured-link first, and
    # front-load the chunk slices that cover the slowest parties (the
    # schedule is advisory for latency, load-bearing for audit — the
    # note below lands in the rpcz session span)
    if link_profile is None:
        link_profile = _default_link_profile()
    party_order, auto_chunk_order, sched_note = schedule_session_order(
        party_ids, link_profile, chunks
    )
    if chunk_order is None:
        chunk_order = auto_chunk_order
    else:
        chunk_order = _validate_chunk_order(chunk_order, chunks)
    sched_extra = (
        (f"quantize={quantize} " if quantize != "none" else "")
        + sched_note
    ).strip()

    # session identity + deadline: what the fault plane keys on.  Every
    # party gets the SAME budget, measured from its own clock at proposal
    # arrival — a partitioned party that never hears the abort broadcast
    # still unwedges at its own deadline.  A caller-supplied session_id
    # is a RESUME of that session: the parties' checkpoint rings are
    # keyed on it.
    import uuid

    if session_id is None:
        session_id = uuid.uuid4().hex
    sess_ms = (
        float(session_deadline_ms)
        if session_deadline_ms and session_deadline_ms > 0
        else float(get_flag("mc_dispatch_session_deadline_ms"))
        or float(timeout_ms)
    )
    ckpt_every = (
        int(checkpoint_every)
        if checkpoint_every is not None
        else int(get_flag("mc_dispatch_checkpoint_every"))
    )
    step_ms = (
        float(step_deadline_ms)
        if step_deadline_ms is not None
        else float(get_flag("mc_dispatch_step_deadline_ms"))
    )
    resume_from = int(resume_from or 0)
    if not (0 <= resume_from <= steps):
        raise ValueError(f"resume_from {resume_from} outside 0..{steps}")

    def proposal(idx: int, nsteps: int, phase: str = "") -> bytes:
        d = {
            "parties": party_ids,
            "index": idx,
            "steps": nsteps,
            "width": dm.width,
            "service": service,
            "method": method,
            "fingerprint": fingerprint,
        }
        if quantize != "none":
            # session-uniform, validated at accept AND run: the
            # fingerprint above IS the quantized variant's, so a party
            # missing the variant (or holding a different one) rejects
            # before lockstep like any other kernel divergence
            d["quantize"] = quantize
        if phase:
            d["phase"] = phase
        else:
            # the FULL operand list: each party device-places only the
            # shards it can address (its own, in the mc deployment), but
            # a single-controller party owns every shard and needs them
            d["operands"] = [
                base64.b64encode(op).decode() for op in operands
            ]
            d["session_id"] = session_id
            d["deadline_ms"] = sess_ms
            d["epoch"] = int(epoch)
            # elastic plane: the proposer owns the cadence (uniform
            # across parties — the min-join's "last common step" depends
            # on it) and the step watchdog; a resumed run names the
            # agreed restore point plus bootstrap rows for parties
            # without a ring (the replacement)
            d["checkpoint_every"] = ckpt_every
            d["step_deadline_ms"] = step_ms
            # the overlap schedule is session-uniform: every party must
            # dispatch the same chunk sequence or the sub-collectives
            # cannot rendezvous
            if chunks > 1:
                d["chunks"] = chunks
                if chunk_order != list(range(chunks)):
                    # the topology-derived route rides the run proposal
                    # (session-uniform: every party must dispatch the
                    # same sub-collective sequence to rendezvous)
                    d["chunk_order"] = chunk_order
            if double_buffer:
                d["double_buffer"] = True
            if resume_from > 0:
                d["resume_from"] = resume_from
                # bootstrap rows ride only to the parties that need them
                # (resume_state_slots — the replacements; survivors
                # restore from their own rings): shipping the full state
                # to every party would be N^2 x width control bytes
                if resume_state and (
                    resume_state_slots is None or idx in resume_state_slots
                ):
                    d["resume_state"] = {
                        str(i): {
                            "row": base64.b64encode(bytes(row)).decode(),
                            "n": int(nn),
                        }
                        for i, (row, nn) in resume_state.items()
                    }
        return json.dumps(d).encode()

    # fleet-wide trace: the proposer's ambient trace context (the RPC
    # handler this proposal runs inside, if any) or a fresh trace id
    # rides EVERY control RPC of this session, so each party's handler
    # span + session/step/chunk spans join one cross-process trace —
    # `rpc_view --trace <id> --targets ...` assembles it.  The sampled
    # bit propagates the head-based decision: sessions are heavyweight
    # (one proposal, N parties), so a proposer with rpcz on samples its
    # sessions at the edge and every party honors that.
    from incubator_brpc_tpu.builtin.rpcz import (
        _new_id as _new_trace_id,
        current_trace_context,
        rpcz_enabled,
    )

    amb_trace, amb_parent = current_trace_context()
    session_trace = amb_trace or (_new_trace_id() if rpcz_enabled() else 0)
    session_sampled = 1 if session_trace else 0
    fleet_trace = (session_trace, amb_parent, session_sampled)

    def _call(ch, payload):
        # scheduling rides the host plane — the shared control-call shape
        return _control_call(ch, payload, timeout_ms, trace=fleet_trace)

    # fan-out order: slowest measured link FIRST (TASP) — that party's
    # accept/run RPC needs the longest lead before each barrier; parties
    # with no telemetry keep mesh order at the tail.  The channel list
    # itself stays positional (callers index it by remote slot).
    fan = sorted(
        zip(channels, remote_indexes),
        key=lambda p: party_order.index(p[1]),
    )

    # Phase 1 — accept barrier + the monotone-max step-count join
    accepts = [
        _call(ch, proposal(idx, steps, phase="accept")) for ch, idx in fan
    ]
    deadline = time.monotonic() + timeout_ms / 1000.0
    final = steps
    for cntl, ev in accepts:
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            raise TimeoutError("dispatch peer never acknowledged proposal")
        if cntl.failed():
            raise RuntimeError(
                f"dispatch proposal rejected: {cntl.error_text}"
            )
        ack = json.loads(cntl.response_payload.decode())
        final = max(final, int(ack.get("target", steps)))

    # Phase 2 — run fan-out (async: a sync proposal would deadlock — the
    # first party's collective blocks on parties never told to start),
    # in the same slowest-first order as the accept fan-out
    pending = [_call(ch, proposal(idx, final)) for ch, idx in fan]
    fan_indexes = [idx for _ch, idx in fan]
    from incubator_brpc_tpu.utils.status import ErrorCode

    # connectivity-class failures of a RUN rpc = the party is DEAD for
    # this session (its chain will never converge); anything else is a
    # reject.  Both abort the session — only death feeds the re-propose
    # path's survivor set.
    _DEATH_CODES = frozenset(
        {
            ErrorCode.EFAILEDSOCKET, ErrorCode.EEOF, ErrorCode.ECLOSE,
            ErrorCode.EHOSTDOWN, ErrorCode.ERPCTIMEDOUT, ErrorCode.ELOGOFF,
            ErrorCode.ETIMEDOUT,
        }
    )
    session_deadline = time.monotonic() + sess_ms / 1000.0
    st = _register_session(
        session_id, party_ids, session_deadline, epoch=epoch
    )
    outcome = {"dead": [], "rejects": [], "reason": ""}
    watch_stop = _threading.Event()

    def _broadcast_abort(reason: str, skip) -> None:
        """phase:"abort" to every party not already known dead (async,
        best-effort — each party's own deadline is the backstop)."""
        msg = json.dumps(
            {
                "phase": "abort",
                "session_id": session_id,
                "reason": reason,
                "epoch": int(epoch),
            }
        ).encode()
        for ch, idx in fan:
            if idx in skip:
                continue
            try:
                _call(ch, msg)
            except Exception:
                logger.exception("abort broadcast to party %d failed", idx)

    broadcast_done = [False]

    def _trigger_abort(reason: str) -> None:
        outcome["reason"] = outcome["reason"] or reason
        if not broadcast_done[0]:
            # one broadcast per session: later classifications (a second
            # death found while the first abort settles) add to the
            # outcome but the survivors were already told
            broadcast_done[0] = True
            _broadcast_abort(reason, set(outcome["dead"]))
        abort_session(session_id, reason, epoch=epoch)

    def _watch() -> None:
        # the generalized rejection watch (supersedes the old fixed-50 ms
        # participating-proposer scan): classify every settled run RPC as
        # it lands; on the FIRST death/reject — or the session deadline —
        # abort fabric-wide so survivors (the proposer's own chain
        # included) exit their lockstep loops instead of waiting in a
        # barrier the dead party can never join.  After an abort the
        # watcher KEEPS scanning until every run RPC settles (or the
        # deadline): an ESESSION answer is a SURVIVOR reporting the abort
        # (its link saw the death first, or our broadcast arrived) — not
        # a reject, and never the dead party, which must still be
        # identified for the re-propose path.
        seen = set()
        while not watch_stop.wait(0.01):
            done = True
            now = time.monotonic()
            for (cntl, ev), idx in zip(pending, fan_indexes):
                if not ev.is_set():
                    done = False
                    continue
                if idx in seen or not cntl.failed():
                    continue
                seen.add(idx)
                code = cntl.error_code
                if code == ErrorCode.ESESSION:
                    # cooperative abort report from a LIVING party:
                    # propagate (covers the link-death-detected-remotely
                    # ordering) but blame nobody
                    _trigger_abort(
                        f"party {idx} reported abort: {cntl.error_text}"
                    )
                elif code in _DEATH_CODES:
                    outcome["dead"].append(idx)
                    _trigger_abort(
                        f"party {idx} died mid-session: {cntl.error_text}"
                    )
                else:
                    outcome["rejects"].append((idx, cntl.error_text))
                    _trigger_abort(
                        f"party {idx} rejected the run: {cntl.error_text}"
                    )
            if done:
                return
            if st.abort_event.is_set() and not broadcast_done[0]:
                # aborted from OUTSIDE the rpc plane (the proposer's own
                # link-death hook fired): the survivors still need the
                # broadcast — their links may be fine
                _trigger_abort(st.abort_reason or "session aborted")
            if now > session_deadline:
                _trigger_abort("session deadline exceeded")
                return

    watcher = _threading.Thread(
        target=_watch, name="mc-session-watch", daemon=True
    )
    watcher.start()

    own_elapsed = None
    results: List[Optional[bytes]] = [None] * n
    abort_exc: Optional[SessionAborted] = None
    sched_span = None
    if proposer_index is None and sched_extra:
        # a pure scheduler leaves the audit span too: the quantize mode,
        # chosen link order and the profile it came from must be
        # traceable even when the proposer runs no chain of its own
        # (index=-1 marks the scheduler role)
        sched_span = _start_session_span(
            service, method, fingerprint, party_ids, -1, final,
            trace_id=session_trace, parent_span_id=amb_parent,
            resume_from=resume_from, extra=sched_extra,
            forced=bool(session_sampled),
        )
    try:
        if proposer_index is not None:

            def _own_should_abort():
                if st.abort_event.is_set():
                    return st.abort_reason or "session aborted"
                if time.monotonic() > session_deadline:
                    abort_session(
                        session_id, "session deadline exceeded", epoch=epoch
                    )
                    return "session deadline exceeded"
                return None

            span = _start_session_span(
                service, method, fingerprint, party_ids, proposer_index,
                final, trace_id=session_trace, parent_span_id=amb_parent,
                resume_from=resume_from, extra=sched_extra,
                forced=bool(session_sampled),
            )
            try:
                own_row, own_n, own_elapsed = run_dispatch_session(
                    party_ids, proposer_index, dm, operands,
                    final, service=service, method=method,
                    should_abort=_own_should_abort,
                    session_id=session_id, resume_from=resume_from,
                    resume_state=resume_state,
                    checkpoint_every=ckpt_every, step_deadline_ms=step_ms,
                    session_epoch=epoch,
                    chunks=chunks, double_buffer=double_buffer,
                    chunk_order=chunk_order,
                    trace_id=(
                        span.trace_id if span is not None else session_trace
                    ),
                    parent_span_id=(
                        span.span_id if span is not None else amb_parent
                    ),
                )
            except SessionAborted as e:
                _end_session_span(span, error_code=ErrorCode.ESESSION)
                abort_exc = e
            except Exception:
                dispatch_errors << 1
                _end_session_span(span, error_code=ErrorCode.EINTERNAL)
                # our own chain failed: the peers' chains can never
                # converge either — take the whole session down cleanly
                _trigger_abort("proposer chain failed")
                raise
            else:
                _end_session_span(span)
                results[proposer_index] = dm.unpack(own_row, own_n)

        # Phase 3 — completion barrier; the watcher exits once every run
        # RPC settled, or as soon as it aborted the session
        watcher.join()
        if st.abort_event.is_set() or abort_exc is not None:
            dead = sorted(set(outcome["dead"]))
            survivors = [i for i in range(n) if i not in set(dead)]
            reason = (
                outcome["reason"]
                or (abort_exc.reason if abort_exc is not None else "")
                or st.abort_reason
                or "session aborted"
            )
            raise SessionAborted(
                reason,
                dead_indexes=dead,
                survivor_indexes=survivors,
                rejects=outcome["rejects"],
                session_id=session_id,
                final_steps=final,
            )
        for (cntl, ev), idx in zip(pending, fan_indexes):
            if cntl.failed():  # defensive: the watcher classifies these
                raise RuntimeError(
                    f"dispatch peer failed: {cntl.error_text}"
                )
            resp = json.loads(cntl.response_payload.decode())
            # each party echoes the count it validated AND ran (a proposal
            # below the party's accepted floor is rejected, never silently
            # re-counted) — a mismatch here means a corrupted or stale
            # proposal reached that party
            if int(resp.get("steps", -1)) != final:
                raise RuntimeError(
                    f"party {idx} dispatched {resp.get('steps')} steps, "
                    f"agreed final was {final} — close did not converge"
                )
            results[idx] = base64.b64decode(resp["result"])
        # clean completion: nothing left to resume — release every
        # party's checkpoint ring (best-effort broadcast; the eviction
        # cap is the backstop for a proposer that dies before this)
        if ckpt_every > 0:
            release_checkpoints(session_id)
            msg = json.dumps(
                {"phase": "release", "session_id": session_id}
            ).encode()
            for ch in channels:
                try:
                    _call(ch, msg)
                except Exception:
                    logger.exception("checkpoint release broadcast failed")
    finally:
        watch_stop.set()
        _unregister_session(st)
        if sched_span is not None:
            _end_session_span(
                sched_span,
                error_code=(
                    int(ErrorCode.ESESSION)
                    if (st.abort_event.is_set() or abort_exc is not None)
                    else 0
                ),
            )
    return {
        "results": results,
        "final_steps": final,
        "elapsed_s": own_elapsed,
        "session_id": session_id,
        "resumed_from": resume_from if resume_from > 0 else None,
        "quantize": quantize,
        # the proposer-side wire accounting the dryrun gate and bench
        # compare: bytes every party put on the party axis across the
        # REPLAYED steps (exact rows ship dm.width per party per step;
        # a resumed run only moved steps past the checkpoint — same
        # basis as mc_dispatch_bytes_saved)
        "wire_bytes": dm.wire_bytes() * n * (final - resume_from),
        "link_order": party_order,
        "chunk_order": chunk_order,
    }


def _control_call(ch, payload: bytes, timeout_ms: float, trace=None):
    """One control-stream RPC (resume barrier traffic rides the same
    host-plane method the proposals do).  ``trace`` is the proposer's
    ``(trace_id, parent_span_id, sampled)`` fleet-trace context: stamped
    on the controller so the proposal crosses the wire inside the
    proposer's trace and every party's spans join it."""
    import threading as _threading

    from incubator_brpc_tpu.rpc.controller import Controller
    from incubator_brpc_tpu.transport.device_link import HANDSHAKE_SERVICE

    cntl = Controller(timeout_ms=timeout_ms)
    cntl._force_host = True
    if trace is not None and trace[0]:
        cntl.trace_id = int(trace[0])
        cntl.parent_span_id = int(trace[1])
        cntl.trace_sampled = 1 if trace[2] else 0
    ev = _threading.Event()
    ch.call_method(
        HANDSHAKE_SERVICE, DISPATCH_METHOD, payload, cntl=cntl,
        done=lambda c, _ev=ev: _ev.set(),
    )
    return cntl, ev


def _query_watermarks(
    session_id: str, survivor_pairs, timeout_ms: float
) -> Dict[int, dict]:
    """The resume barrier's gather half: ask every surviving remote party
    for its checkpoint census (phase:"resume_query"), merge with the
    proposer-local census (a participating proposer — and, in-process,
    co-hosted parties — answer from the same registry).  A survivor that
    fails the query contributes nothing, which drags the min-join to 0 —
    the safe side."""
    msg = json.dumps(
        {"phase": "resume_query", "session_id": session_id}
    ).encode()
    calls = []
    for ch, idx in survivor_pairs:
        try:
            calls.append(_control_call(ch, msg, timeout_ms))
        except Exception:
            logger.exception("resume query to party %d failed", idx)
    merged: Dict[int, dict] = dict(checkpoint_watermarks(session_id))
    deadline = time.monotonic() + timeout_ms / 1000.0
    for cntl, ev in calls:
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            continue
        if cntl.failed():
            continue
        try:
            ans = json.loads(cntl.response_payload.decode())
            for k, info in (ans.get("watermarks") or {}).items():
                slot = int(k)
                have = merged.get(slot)
                if have is None or int(info.get("watermark", 0)) > int(
                    have.get("watermark", 0)
                ):
                    merged[slot] = info
        except (ValueError, TypeError, AttributeError):
            continue
    return merged


def _fetch_state(
    session_id: str,
    step: int,
    slots: List[int],
    channels,
    timeout_ms: float,
    required=None,
) -> Optional[Dict[int, Tuple[bytes, int]]]:
    """Reshard: assemble session state at one checkpointed step from the
    survivors' rings (local first, then phase:"fetch_shard" over the rpc
    plane) — what bootstraps a replacement party.  Returns
    {slot: (full-width row bytes, n)} covering whatever was reachable,
    or None when a REQUIRED slot (default: all of ``slots``) is
    unrecoverable — the caller then falls back to a full restart.  On a
    true multi-controller fabric each survivor serves only its own slot,
    so asking for every slot with ``required`` = the replaced ones gets
    the replacement everything reachable without failing the resume on
    rows nobody can provide."""
    # local rings first, raw (no b64 round trip for rows already here)
    state: Dict[int, Tuple[bytes, int]] = dict(
        _checkpoint_rows(session_id, step, slots)
    )

    def _absorb(rows: Dict) -> None:
        for k, v in rows.items():
            slot = int(k)
            if slot not in state:
                state[slot] = (base64.b64decode(v["row"]), int(v["n"]))

    for ch in channels:
        missing = [s for s in slots if s not in state]
        if not missing:
            break
        msg = json.dumps(
            {
                "phase": "fetch_shard",
                "session_id": session_id,
                "step": int(step),
                "slots": missing,
            }
        ).encode()
        try:
            cntl, ev = _control_call(ch, msg, timeout_ms)
        except Exception:
            logger.exception("shard fetch failed")
            continue
        if not ev.wait(timeout_ms / 1000.0) or cntl.failed():
            continue
        try:
            _absorb(
                json.loads(cntl.response_payload.decode()).get("rows") or {}
            )
        except (ValueError, TypeError, KeyError, AttributeError):
            continue
    need = slots if required is None else required
    if any(s not in state for s in need):
        return None
    return state


def propose_with_recovery(
    channels,
    party_ids: List[int],
    service: str,
    method: str,
    operands: List[bytes],
    steps: int = 1,
    proposer_index: Optional[int] = None,
    timeout_ms: float = 120000,
    session_deadline_ms: Optional[float] = None,
    max_reproposals: int = 1,
    spares=None,
    checkpoint_every: Optional[int] = None,
    step_deadline_ms: Optional[float] = None,
    chunks: int = 1,
    double_buffer: bool = False,
    quantize: str = "none",
    link_profile=None,
) -> dict:
    """:func:`propose_dispatch` with the elastic recovery path: a session
    that aborts on PARTY DEATH heals instead of restarting from nothing
    (up to ``max_reproposals`` times).  Two recovery modes, tried in
    order:

    1. **Resume with replacement** — when ``spares`` (a list of
       ``(channel, device_id)`` standby parties) can fill every dead
       slot: the resume barrier min-joins the survivors' checkpoint
       watermarks into the last COMMON checkpointed step, the dead
       party's state is re-sharded out of the survivors' rings over the
       rpc plane, and the SAME session (same id, same party-set width,
       same agreed step count) re-runs only the steps past the resume
       point — byte-identical to an undisturbed run.  Zero common
       checkpoint falls back to a full restart, still over the healed
       party set.
    2. **Shrink restart** — no spare: the PR-8 path, a fresh session
       from step 0 over the survivors only (an axis-reducing kernel
       cannot RESUME with fewer parties — re-running checkpointed-past
       steps with a divergent party set is exactly what the fabricverify
       resume model forbids).

    Rejects and proposer death are not recoverable and re-raise.  The
    result dict gains ``dead_party_ids``, ``replaced_party_ids`` and
    ``resumed_from`` (None unless the winning run was a resume)."""
    chs = list(channels)
    pids = [int(p) for p in party_ids]
    ops = list(operands)
    pidx = proposer_index
    dropped: List[int] = []
    replaced: List[int] = []
    spare_pool = list(spares or ())
    import uuid

    session_id = uuid.uuid4().hex
    run_steps = steps
    resume_from = 0
    resume_state: Optional[Dict[int, Tuple[bytes, int]]] = None
    resumed = False
    for attempt in range(max_reproposals + 1):
        remote = [i for i in range(len(pids)) if i != pidx]
        try:
            out = propose_dispatch(
                chs, pids, service, method, ops, steps=run_steps,
                proposer_index=pidx, timeout_ms=timeout_ms,
                session_deadline_ms=session_deadline_ms,
                session_id=session_id, resume_from=resume_from,
                resume_state=resume_state,
                resume_state_slots=frozenset(
                    i for i in range(len(pids))
                    if pids[i] in set(replaced)
                ) or None,
                checkpoint_every=checkpoint_every,
                step_deadline_ms=step_deadline_ms,
                epoch=attempt,
                chunks=chunks, double_buffer=double_buffer,
                quantize=quantize, link_profile=link_profile,
            )
            out["dead_party_ids"] = dropped
            out["replaced_party_ids"] = replaced
            if resumed:
                dispatch_resumes << 1
            return out
        except SessionAborted as e:
            dead = set(e.dead_indexes)
            if (
                attempt == max_reproposals
                or not dead
                or e.rejects
                or (pidx is not None and pidx in dead)
            ):
                raise
            have_spares = len(spare_pool) >= len(dead)
            if not have_spares and len(pids) - len(dead) < 2:
                # a shrink below 2 parties is no session; replacement
                # does not shrink, so the width guard only gates mode 2
                raise
            run_steps = max(run_steps, e.final_steps or 0)
            if have_spares:
                # elastic heal: replacement + resume (mode 1)
                dropped.extend(pids[i] for i in sorted(dead))
                survivor_slots = [
                    i for i in range(len(pids)) if i not in dead
                ]
                surv_pairs = [
                    (ch, idx)
                    for ch, idx in zip(chs, remote)
                    if idx not in dead
                ]
                wms = _query_watermarks(session_id, surv_pairs, timeout_ms)
                point = resume_point(
                    {i: wms.get(i) for i in survivor_slots}
                )
                for slot in sorted(dead):
                    sch, sdev = spare_pool.pop(0)
                    replaced.append(int(sdev))
                    pids[slot] = int(sdev)
                    chs[remote.index(slot)] = sch
                state = None
                if point > 0:
                    # reshard for the REPLACEMENTS: gather every slot
                    # reachable at the resume point (a single-controller
                    # replacement addresses all slots; a true
                    # multi-controller one only its own), but REQUIRE
                    # only the replaced slots — survivors restore their
                    # slots from their own rings, and the bootstrap rows
                    # ride only to the replacement parties
                    # (resume_state_slots below).  A dead slot no
                    # reachable ring covers (a true mc fabric, where the
                    # dead party's ring died with it) forces the
                    # full-restart fallback — still over the healed set.
                    state = _fetch_state(
                        session_id, point, list(range(len(pids))),
                        [ch for ch, _i in surv_pairs], timeout_ms,
                        required=sorted(dead),
                    )
                    if state is None:
                        point = 0  # reshard incomplete: full restart
                resume_from = point
                resume_state = state
                resumed = True
                dispatch_replaced_parties << len(dead)
                logger.warning(
                    "resuming %s.%s session %s from step %d with %d "
                    "replacement(s) after: %s",
                    service, method, session_id, point, len(dead),
                    e.reason,
                )
            else:
                # shrink restart (mode 2): new session over the
                # survivors; the old session's rings are released
                # best-effort (the eviction cap is the backstop)
                dropped.extend(pids[i] for i in sorted(dead))
                logger.warning(
                    "re-proposing %s.%s over %d survivor(s) after: %s",
                    service, method, len(pids) - len(dead), e.reason,
                )
                release_checkpoints(session_id)
                rel = json.dumps(
                    {"phase": "release", "session_id": session_id}
                ).encode()
                keep = [i for i in range(len(pids)) if i not in dead]
                chs = [
                    ch for ch, idx in zip(chs, remote) if idx not in dead
                ]
                for ch in chs:
                    try:
                        _control_call(ch, rel, timeout_ms)
                    except Exception:
                        logger.exception("checkpoint release failed")
                ops = [ops[i] for i in keep]
                pids = [pids[i] for i in keep]
                if pidx is not None:
                    pidx = keep.index(pidx)
                session_id = uuid.uuid4().hex
                resume_from = 0
                resume_state = None
                resumed = False
    raise AssertionError("unreachable")


# -- the ParallelChannel lowering ----------------------------------------------

mc_lowered_dispatches = Adder(name="parallel_channel_mc_lowered")


def lower_parallel_call(
    channels,
    devices,
    service: str,
    method: str,
    requests: List[bytes],
    timeout_ms: float,
) -> List[bytes]:
    """One combo call lowered onto the method plane: the sub-channels'
    server devices form the party axis (channel order — the same order
    the single-controller fused dispatch stacks, so merges are
    byte-identical), each party's operand is its sub-request, the
    proposer is a pure scheduler (its process cannot address any party
    device), and one 1-step session replaces the host fan-out. Returns
    per-sub response bytes in channel order.

    Resume is transparent here: the call routes through
    :func:`propose_with_recovery`, so a multi-step lowering (or a future
    combo batching several steps into one session) heals the same way a
    direct session does.  A 1-step session has no checkpointed past and
    no spare pool, so an abort still surfaces as :class:`SessionAborted`
    and the combo layer falls back to the host fan-out — unchanged
    semantics, one recovery plane."""
    if not timeout_ms or timeout_ms <= 0:
        timeout_ms = 120000.0
    out = propose_with_recovery(
        channels,
        [d.id for d in devices],
        service,
        method,
        requests,
        steps=1,
        proposer_index=None,
        timeout_ms=timeout_ms,
        max_reproposals=0,
    )
    mc_lowered_dispatches << 1
    return out["results"]
