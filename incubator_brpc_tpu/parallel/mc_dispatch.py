"""Collective method plane — ANY registered device method, fabric-wide.

`parallel/mc_collective.py` proved the pipelined cross-controller session
shape (schedule once over the host plane, run K lockstep shard_map steps
with operands device-resident through the chain) — but its kernel was
hardcoded pmean, a canned demo. The single-controller fused dispatch
(`rpc/combo.py`) already runs arbitrary user-registered device methods
(`rpc/device_method.py`) with fingerprint validation, and the mc handshake
advertises those fingerprints (`transport/mc_link.py`) — this module
closes that loop, the way the reference transport carries *arbitrary*
registered methods rather than one canned op (protocol.h:64-158):

- **A session names a (service, method) pair.** The proposal carries the
  pair, the kernel fingerprint the proposer resolved, the row geometry,
  the step count and each party's initial operand. Nothing about the
  kernel's body crosses the wire — only its identity.
- **Every party validates before entering lockstep.** Each party — the
  proposer included — resolves the pair against its LOCAL registry and
  compares fingerprints. A mismatch (same name, different kernel — the
  divergence that would silently corrupt a lockstep chain) is a clean
  reject on the control stream: the proposer surfaces it before any
  party dispatches a collective that could never rendezvous.
- **The shared step binds the resolved kernel.** All parties jit the
  IDENTICAL program: ``shard_map`` over ``Mesh(parties, ("par",))`` —
  the SAME axis name the single-controller fused dispatch binds, so a
  kernel that reduces over the axis (psum gradients, all-to-all experts)
  behaves identically on both planes — applied K times with the chain's
  operands never leaving the devices.
- **N parties, convergent close.** The proposal fans out over the star
  (one host channel per remote party), a barrier collects every accept,
  and the final step count is the monotone max of every party's accept
  target — the 2-party close dance's ``max(targets)`` join generalized
  to N. All parties dispatch exactly ``final`` steps; each run response
  echoes the count and the proposer asserts convergence.

`ParallelChannel._fused_dispatch` lowers through this plane when its
sub-channels resolve to multi-controller links (one shard_map dispatch is
impossible across controllers — the client cannot place bytes on
non-addressable devices), so the single-controller fused path and the
cross-process path present ONE API: register a device method, call the
combo channel, and the transport picks the lowering.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from incubator_brpc_tpu.bvar import Adder, LatencyRecorder
from incubator_brpc_tpu.utils.flags import define_flag, get_flag

logger = logging.getLogger(__name__)

define_flag(
    "mc_dispatch_min_steps",
    0,
    "minimum step count this party accepts into a collective-method "
    "session: its accept ack raises the session target to at least this "
    "(the proposer folds every target with max — the N-party join)",
    lambda v: v >= 0,
)

DISPATCH_METHOD = "collective_dispatch"

# Bounds a proposal must sit inside before anything is resolved or run
# (mirrors mc_collective's admission checks).
MAX_STEPS = 100_000
MAX_WIDTH = 1 << 20
MAX_PARTIES = 1024

# How long the proposer watches freshly-dispatched RUN proposals for an
# instant bounce before entering its own session (see mc_collective's
# _REJECT_WATCH_S — same rationale, same bound).
_REJECT_WATCH_S = 0.05

# plane-level observability: sessions/steps/errors/rejects across every
# kernel, plus a latency summary; per-kernel counters are minted lazily
# below so /vars and /brpc_metrics can tell WHICH methods ride the plane
dispatch_sessions = Adder(name="mc_dispatch_sessions")
dispatch_steps = Adder(name="mc_dispatch_steps")
dispatch_errors = Adder(name="mc_dispatch_errors")
dispatch_rejects = Adder(name="mc_dispatch_rejects")
dispatch_session_us = LatencyRecorder(name="mc_dispatch_session_us")

_method_counters: Dict[Tuple[str, str], Adder] = {}
_method_counters_lock = threading.Lock()


def _method_counter(service: str, method: str) -> Adder:
    """Per-kernel session counter (``mc_dispatch_<svc>_<m>_sessions``),
    minted on first use — the bvar registry keeps it scrapeable."""
    key = (service, method)
    with _method_counters_lock:
        ctr = _method_counters.get(key)
        if ctr is None:
            safe = "_".join(
                "".join(c if c.isalnum() else "_" for c in part)
                for part in key
            )
            ctr = Adder(name=f"mc_dispatch_{safe}_sessions")
            _method_counters[key] = ctr
        return ctr


# -- kernel resolution ---------------------------------------------------------

# Fallback resolvers for builtin kernels that are minted per-geometry
# rather than registered by a Server (mc_collective's pmean installs one).
# Signature: (service, method, width_bytes) -> Optional[DeviceMethod].
_resolvers: List[Callable] = []


def register_method_resolver(fn: Callable) -> None:
    if fn not in _resolvers:
        _resolvers.append(fn)


def resolve_method(service: str, method: str, width: Optional[int] = None):
    """Resolve (service, method) to this process's DeviceMethod: the
    process-global registry first (what Server.add_service fills), then
    the builtin resolvers. ``width`` (row bytes) must match the resolved
    geometry — a session whose parties disagree on geometry could never
    exchange shards."""
    from incubator_brpc_tpu.rpc.device_method import lookup_device_method

    dm = lookup_device_method(service, method)
    if dm is None:
        for r in list(_resolvers):
            dm = r(service, method, width)
            if dm is not None:
                break
    if dm is None:
        return None
    if width is not None and dm.width != width:
        return None
    return dm


def _devices_by_id(ids: List[int]):
    import jax

    by_id = {d.id: d for d in jax.devices()}
    try:
        return [by_id[i] for i in ids]
    except KeyError as e:
        raise ValueError(
            f"device id {e} not in this process's global view "
            f"(is jax.distributed initialized everywhere?)"
        )


# -- the shared lockstep step --------------------------------------------------


_step_cache: Dict[tuple, tuple] = {}  # (fp, party ids) -> (step_fn, dm)
_step_cache_lock = threading.Lock()


def _make_step(dm, mesh, sharding, party_ids):
    """The identical jitted program every party dispatches: one shard_map
    application of the resolved kernel over the party axis. Axis name
    "par" matches the single-controller fused dispatch (rpc/combo.py), so
    axis-reducing kernels produce the same bytes on both planes. Cached
    per (kernel fingerprint, party set): the ParallelChannel lowering
    runs one session per combo CALL, and re-tracing every call would put
    XLA compilation on the request path (combo's _fused_cache, here)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    key = (dm.fingerprint(), tuple(party_ids))
    with _step_cache_lock:
        cached = _step_cache.get(key)
        if cached is not None and cached[1] is not dm:
            cached = None  # same name re-registered with a new DeviceMethod
        if cached is None:

            def body(data, ns):
                out, m = dm.kernel(data[0], ns[0])
                return out[None], m[None]

            wrapped = shard_map_compat(
                body, mesh=mesh, in_specs=(P("par"), P("par")),
                out_specs=(P("par"), P("par")),
            )
            cached = (
                jax.jit(wrapped, out_shardings=(sharding, sharding)), dm
            )
            _step_cache[key] = cached
    return cached[0]


def run_dispatch_session(
    party_ids: List[int],
    own_index: int,
    dm,
    operands: List[bytes],
    steps: int,
    service: str = "?",
    method: str = "?",
) -> Tuple[np.ndarray, int, float]:
    """Run this party's side of a K-step session of ``dm``'s kernel;
    returns (own final row, own final n, elapsed seconds). Every party
    calls this with identical arguments except ``own_index`` — the jitted
    programs must match or the collectives cannot rendezvous. Each party
    device-places the shards it can ADDRESS: in the multi-controller
    deployment that is exactly its own row (the peers' devices are
    visible but not addressable — they contribute their shards from their
    own processes); in a single-controller run one call owns every shard
    and the session degenerates to the full computation. Operands stay
    device-resident across the chain: only the initial device_put and the
    final fetch cross the host boundary, and XLA pipelines the K
    dispatches (the ack/credit discipline is the response barrier the
    proposer collects — no per-step coordination)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = _devices_by_id(party_ids)
    n = len(devices)
    if len(operands) != n:
        raise ValueError("one operand per party required")
    mesh = Mesh(np.asarray(devices), ("par",))
    sharding = NamedSharding(mesh, P("par"))
    step_fn = _make_step(dm, mesh, sharding, party_ids)

    addressable = sharding.addressable_devices
    own_dev = devices[own_index]
    if own_dev not in addressable:
        raise ValueError(
            f"party {own_index} device {own_dev} is not addressable from "
            f"this process"
        )
    row_shards, n_shards = [], []
    for i, dev in enumerate(devices):
        if dev not in addressable:
            continue
        row, nn = dm.pack(operands[i])
        row_shards.append(jax.device_put(row[None, :], dev))
        n_shards.append(
            jax.device_put(np.asarray([nn], dtype=np.int32), dev)
        )
    x = jax.make_array_from_single_device_arrays(
        (n, dm.width), sharding, row_shards
    )
    ns = jax.make_array_from_single_device_arrays((n,), sharding, n_shards)
    t0 = time.perf_counter()
    for _ in range(steps):
        x, ns = step_fn(x, ns)  # chained: operands never leave the devices
    own_row = own_n = None
    for s in x.addressable_shards:
        # a process can address several mesh devices (single-controller
        # runs): OUR shard is the one on devices[own_index]
        if s.device == own_dev:
            own_row = np.asarray(s.data).reshape(-1)
    for s in ns.addressable_shards:
        if s.device == own_dev:
            own_n = int(np.asarray(s.data).reshape(-1)[0])
    elapsed = time.perf_counter() - t0
    assert own_row is not None and own_n is not None
    dispatch_sessions << 1
    dispatch_steps << steps
    dispatch_session_us << elapsed * 1e6
    _method_counter(service, method) << 1
    return own_row, own_n, elapsed


# -- rpcz spans (annotated with method identity) -------------------------------


def _start_session_span(
    service: str,
    method: str,
    fingerprint: str,
    party_ids: List[int],
    own_index: int,
    steps: int,
    trace_id: int = 0,
    parent_span_id: int = 0,
):
    from incubator_brpc_tpu.builtin.rpcz import (
        SPAN_TYPE_COLLECTIVE,
        start_custom_span,
    )

    span = start_custom_span(
        SPAN_TYPE_COLLECTIVE,
        service,
        method,
        trace_id=trace_id,
        parent_span_id=parent_span_id,
    )
    if span is not None:
        span.annotate(
            f"method={service}.{method} fingerprint={fingerprint} "
            f"steps={steps} index={own_index} parties={party_ids}"
        )
    return span


def _end_session_span(span, error_code: int = 0) -> None:
    from incubator_brpc_tpu.builtin.rpcz import end_custom_span

    end_custom_span(span, error_code=error_code)


# -- server half ---------------------------------------------------------------


def _validate_proposal(req: dict):
    """Shared accept/run admission: bounds, then kernel identity. Returns
    (party_ids, own_index, steps, dm, err) where err is (code, text) on
    rejection — the clean control-stream reject that keeps a divergent
    party out of lockstep."""
    from incubator_brpc_tpu.utils.status import ErrorCode

    try:
        party_ids = [int(i) for i in req["parties"]]
        own_index = int(req["index"])
        steps = int(req["steps"])
        width = int(req["width"])
        service = str(req["service"])
        method = str(req["method"])
        fingerprint = str(req["fingerprint"])
    except (ValueError, KeyError, TypeError) as e:
        return None, None, None, None, (
            ErrorCode.EREQUEST, f"bad dispatch proposal: {e}"
        )
    if not (
        0 < steps <= MAX_STEPS
        and 0 < width <= MAX_WIDTH
        and 1 < len(party_ids) <= MAX_PARTIES
        and 0 <= own_index < len(party_ids)
        and len(set(party_ids)) == len(party_ids)
    ):
        return None, None, None, None, (
            ErrorCode.EREQUEST, "dispatch proposal out of bounds"
        )
    dm = resolve_method(service, method, width)
    if dm is None:
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.ENOMETHOD,
            f"no device method {service}.{method} with width {width} "
            f"registered in this process",
        )
    ours = dm.fingerprint()
    if ours != fingerprint:
        # same name, different kernel: entering lockstep would run a
        # program the proposer never named — reject before any dispatch
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.EREQUEST,
            f"device method fingerprint mismatch for {service}.{method}: "
            f"proposal {fingerprint} vs local {ours}",
        )
    try:
        _devices_by_id(party_ids)
    except ValueError as e:
        return None, None, None, None, (ErrorCode.EREQUEST, str(e))
    return party_ids, own_index, steps, dm, None


def make_dispatch_handler(server):
    """Server half of ``_tpu_transport.collective_dispatch``: validate a
    session proposal against the local registry (accept phase — nothing
    runs), or bind the resolved kernel and run this party's side of the
    lockstep chain (run phase), answering with the final shard."""

    def collective_dispatch(cntl, request: bytes) -> bytes:
        try:
            req = json.loads(request.decode())
        except ValueError as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"undecodable proposal: {e}")
            return b""
        party_ids, own_index, steps, dm, err = _validate_proposal(req)
        if err is not None:
            cntl.set_failed(*err)
            return b""
        service, method = str(req["service"]), str(req["method"])
        floor = int(get_flag("mc_dispatch_min_steps"))
        if req.get("phase") != "accept" and steps < floor:
            # the accept ack raised our target to the floor; a run
            # proposal below it means the proposer did not fold this
            # party's target — reject rather than silently dispatch a
            # count the accept never agreed to (the close-barrier echo
            # below only proves the VALIDATED count was run)
            from incubator_brpc_tpu.utils.status import ErrorCode

            dispatch_rejects << 1
            cntl.set_failed(
                ErrorCode.EREQUEST,
                f"run proposal steps {steps} below this party's accepted "
                f"floor {floor}",
            )
            return b""
        if req.get("phase") == "accept":
            # Nothing is run or reserved; ``target`` lets this party RAISE
            # the step count (mc_dispatch_min_steps — e.g. a pipeline-depth
            # floor). The proposer folds every target with max — the
            # 2-party close dance's max(targets) join, generalized to N.
            target = min(
                max(steps, int(get_flag("mc_dispatch_min_steps"))), MAX_STEPS
            )
            return json.dumps(
                {"accept": True, "index": own_index, "target": target}
            ).encode()
        try:
            operands = [
                base64.b64decode(op) for op in req.get("operands", [])
            ]
            if len(operands) != len(party_ids):
                raise ValueError("one operand per party required")
            for op in operands:
                if len(op) > dm.width:
                    raise ValueError(
                        f"operand of {len(op)}B exceeds width {dm.width}"
                    )
        except (ValueError, TypeError) as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"bad operands: {e}")
            return b""
        span = _start_session_span(
            service, method, dm.fingerprint(), party_ids, own_index, steps,
            trace_id=cntl.trace_id, parent_span_id=cntl.span_id,
        )
        try:
            own_row, own_n, elapsed = run_dispatch_session(
                party_ids, own_index, dm, operands, steps,
                service=service, method=method,
            )
        except Exception as e:
            dispatch_errors << 1
            from incubator_brpc_tpu.utils.status import ErrorCode

            _end_session_span(span, error_code=ErrorCode.EINTERNAL)
            logger.exception("dispatch session failed")
            cntl.set_failed(ErrorCode.EINTERNAL, f"dispatch session: {e!r}")
            return b""
        _end_session_span(span)
        return json.dumps(
            {
                "result": base64.b64encode(
                    dm.unpack(own_row, own_n)
                ).decode(),
                "steps": steps,
                "elapsed_s": elapsed,
                "index": own_index,
            }
        ).encode()

    return collective_dispatch


# -- client half: the N-party session scheduler --------------------------------


def propose_dispatch(
    channels,
    party_ids: List[int],
    service: str,
    method: str,
    operands: List[bytes],
    steps: int = 1,
    proposer_index: Optional[int] = None,
    timeout_ms: float = 120000,
) -> dict:
    """Schedule an N-party session of a registered device method.

    ``party_ids`` are global device ids in mesh order; ``operands[i]`` is
    party i's initial row. ``channels[j]`` is a host channel to the
    server playing the j-th REMOTE party index (every index except
    ``proposer_index``; with ``proposer_index=None`` the proposer is a
    pure scheduler and every party is remote — the ParallelChannel
    lowering's shape). Returns ``{"results": [bytes per party],
    "final_steps": k, "elapsed_s": proposer's chain seconds or None}``.

    Three phases over the star:
    1. accept fan-out + barrier — every party resolves the (service,
       method) pair locally and fingerprint-checks it; any reject
       surfaces HERE, before lockstep. ``final = max(all targets)``.
    2. run fan-out (async — every party must be dispatching before any
       can finish) with a short rejection watch, then the proposer's own
       chain if it participates.
    3. completion barrier — every response must echo ``final`` (the
       convergent close: all parties dispatched exactly the same count).
    """
    import threading as _threading

    from incubator_brpc_tpu.rpc.controller import Controller
    from incubator_brpc_tpu.transport.device_link import HANDSHAKE_SERVICE

    n = len(party_ids)
    remote_indexes = [i for i in range(n) if i != proposer_index]
    if len(remote_indexes) != len(channels):
        raise ValueError("one channel per remote party required")
    if len(operands) != n:
        raise ValueError("one operand per party required")
    dm = resolve_method(service, method)
    if dm is None:
        raise LookupError(
            f"device method {service}.{method} not registered locally "
            f"(the proposer validates against its own registry too)"
        )
    fingerprint = dm.fingerprint()
    for op in operands:
        if len(op) > dm.width:
            raise ValueError(
                f"operand of {len(op)}B exceeds method width {dm.width}"
            )

    def proposal(idx: int, nsteps: int, phase: str = "") -> bytes:
        d = {
            "parties": party_ids,
            "index": idx,
            "steps": nsteps,
            "width": dm.width,
            "service": service,
            "method": method,
            "fingerprint": fingerprint,
        }
        if phase:
            d["phase"] = phase
        else:
            # the FULL operand list: each party device-places only the
            # shards it can address (its own, in the mc deployment), but
            # a single-controller party owns every shard and needs them
            d["operands"] = [
                base64.b64encode(op).decode() for op in operands
            ]
        return json.dumps(d).encode()

    def _call(ch, payload):
        cntl = Controller(timeout_ms=timeout_ms)
        cntl._force_host = True  # scheduling rides the host plane
        ev = _threading.Event()
        ch.call_method(
            HANDSHAKE_SERVICE,
            DISPATCH_METHOD,
            payload,
            cntl=cntl,
            done=lambda c, _ev=ev: _ev.set(),
        )
        return cntl, ev

    # Phase 1 — accept barrier + the monotone-max step-count join
    accepts = [
        _call(ch, proposal(idx, steps, phase="accept"))
        for ch, idx in zip(channels, remote_indexes)
    ]
    deadline = time.monotonic() + timeout_ms / 1000.0
    final = steps
    for cntl, ev in accepts:
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            raise TimeoutError("dispatch peer never acknowledged proposal")
        if cntl.failed():
            raise RuntimeError(
                f"dispatch proposal rejected: {cntl.error_text}"
            )
        ack = json.loads(cntl.response_payload.decode())
        final = max(final, int(ack.get("target", steps)))

    # Phase 2 — run fan-out (async: a sync proposal would deadlock — the
    # first party's collective blocks on parties never told to start)
    pending = [
        _call(ch, proposal(idx, final))
        for ch, idx in zip(channels, remote_indexes)
    ]
    if proposer_index is not None:
        # Rejection watch before committing OUR device to a collective
        # that could never rendezvous. A scheduler-only proposer skips
        # it: it runs no collective, and phase 3 surfaces the same
        # rejects — burning a fixed 50 ms there would tax every
        # mc-lowered ParallelChannel call (and the LB latency feedback).
        watch_deadline = time.monotonic() + _REJECT_WATCH_S
        while time.monotonic() < watch_deadline:
            for cntl, ev in pending:
                if ev.is_set() and cntl.failed():
                    raise RuntimeError(
                        f"dispatch proposal rejected: {cntl.error_text}"
                    )
            if all(ev.is_set() for _c, ev in pending):
                break  # every run already answered; nothing to watch
            time.sleep(0.005)
    own_elapsed = None
    results: List[Optional[bytes]] = [None] * n
    if proposer_index is not None:
        span = _start_session_span(
            service, method, fingerprint, party_ids, proposer_index, final
        )
        try:
            own_row, own_n, own_elapsed = run_dispatch_session(
                party_ids, proposer_index, dm, operands,
                final, service=service, method=method,
            )
        except Exception:
            dispatch_errors << 1
            from incubator_brpc_tpu.utils.status import ErrorCode

            _end_session_span(span, error_code=ErrorCode.EINTERNAL)
            raise
        _end_session_span(span)
        results[proposer_index] = dm.unpack(own_row, own_n)

    # Phase 3 — completion barrier; every response must echo ``final``
    deadline = time.monotonic() + timeout_ms / 1000.0
    for (cntl, ev), idx in zip(pending, remote_indexes):
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            raise TimeoutError("dispatch peer never completed")
        if cntl.failed():
            raise RuntimeError(f"dispatch peer failed: {cntl.error_text}")
        resp = json.loads(cntl.response_payload.decode())
        # each party echoes the count it validated AND ran (a proposal
        # below the party's accepted floor is rejected, never silently
        # re-counted) — a mismatch here means a corrupted or stale
        # proposal reached that party
        if int(resp.get("steps", -1)) != final:
            raise RuntimeError(
                f"party {idx} dispatched {resp.get('steps')} steps, "
                f"agreed final was {final} — close did not converge"
            )
        results[idx] = base64.b64decode(resp["result"])
    return {"results": results, "final_steps": final, "elapsed_s": own_elapsed}


# -- the ParallelChannel lowering ----------------------------------------------

mc_lowered_dispatches = Adder(name="parallel_channel_mc_lowered")


def lower_parallel_call(
    channels,
    devices,
    service: str,
    method: str,
    requests: List[bytes],
    timeout_ms: float,
) -> List[bytes]:
    """One combo call lowered onto the method plane: the sub-channels'
    server devices form the party axis (channel order — the same order
    the single-controller fused dispatch stacks, so merges are
    byte-identical), each party's operand is its sub-request, the
    proposer is a pure scheduler (its process cannot address any party
    device), and one 1-step session replaces the host fan-out. Returns
    per-sub response bytes in channel order."""
    if not timeout_ms or timeout_ms <= 0:
        timeout_ms = 120000.0
    out = propose_dispatch(
        channels,
        [d.id for d in devices],
        service,
        method,
        requests,
        steps=1,
        proposer_index=None,
        timeout_ms=timeout_ms,
    )
    mc_lowered_dispatches << 1
    return out["results"]
