"""Collective method plane — ANY registered device method, fabric-wide.

`parallel/mc_collective.py` proved the pipelined cross-controller session
shape (schedule once over the host plane, run K lockstep shard_map steps
with operands device-resident through the chain) — but its kernel was
hardcoded pmean, a canned demo. The single-controller fused dispatch
(`rpc/combo.py`) already runs arbitrary user-registered device methods
(`rpc/device_method.py`) with fingerprint validation, and the mc handshake
advertises those fingerprints (`transport/mc_link.py`) — this module
closes that loop, the way the reference transport carries *arbitrary*
registered methods rather than one canned op (protocol.h:64-158):

- **A session names a (service, method) pair.** The proposal carries the
  pair, the kernel fingerprint the proposer resolved, the row geometry,
  the step count and each party's initial operand. Nothing about the
  kernel's body crosses the wire — only its identity.
- **Every party validates before entering lockstep.** Each party — the
  proposer included — resolves the pair against its LOCAL registry and
  compares fingerprints. A mismatch (same name, different kernel — the
  divergence that would silently corrupt a lockstep chain) is a clean
  reject on the control stream: the proposer surfaces it before any
  party dispatches a collective that could never rendezvous.
- **The shared step binds the resolved kernel.** All parties jit the
  IDENTICAL program: ``shard_map`` over ``Mesh(parties, ("par",))`` —
  the SAME axis name the single-controller fused dispatch binds, so a
  kernel that reduces over the axis (psum gradients, all-to-all experts)
  behaves identically on both planes — applied K times with the chain's
  operands never leaving the devices.
- **N parties, convergent close.** The proposal fans out over the star
  (one host channel per remote party), a barrier collects every accept,
  and the final step count is the monotone max of every party's accept
  target — the 2-party close dance's ``max(targets)`` join generalized
  to N. All parties dispatch exactly ``final`` steps; each run response
  echoes the count and the proposer asserts convergence.

`ParallelChannel._fused_dispatch` lowers through this plane when its
sub-channels resolve to multi-controller links (one shard_map dispatch is
impossible across controllers — the client cannot place bytes on
non-addressable devices), so the single-controller fused path and the
cross-process path present ONE API: register a device method, call the
combo channel, and the transport picks the lowering.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from incubator_brpc_tpu.bvar import Adder, LatencyRecorder
from incubator_brpc_tpu.utils.flags import define_flag, get_flag

logger = logging.getLogger(__name__)

define_flag(
    "mc_dispatch_min_steps",
    0,
    "minimum step count this party accepts into a collective-method "
    "session: its accept ack raises the session target to at least this "
    "(the proposer folds every target with max — the N-party join)",
    lambda v: v >= 0,
)

define_flag(
    "mc_dispatch_session_deadline_ms",
    0,
    "default per-session deadline for collective-method sessions: a "
    "session older than this aborts fabric-wide with ESESSION (every "
    "party watches its own copy, so a partitioned party still unwedges); "
    "0 = inherit the proposal's RPC timeout",
    lambda v: v >= 0,
)

DISPATCH_METHOD = "collective_dispatch"

# Bounds a proposal must sit inside before anything is resolved or run
# (mirrors mc_collective's admission checks).
MAX_STEPS = 100_000
MAX_WIDTH = 1 << 20
MAX_PARTIES = 1024

# plane-level observability: sessions/steps/errors/rejects across every
# kernel, plus a latency summary; per-kernel counters are minted lazily
# below so /vars and /brpc_metrics can tell WHICH methods ride the plane
dispatch_sessions = Adder(name="mc_dispatch_sessions")
dispatch_steps = Adder(name="mc_dispatch_steps")
dispatch_errors = Adder(name="mc_dispatch_errors")
dispatch_rejects = Adder(name="mc_dispatch_rejects")
dispatch_aborts = Adder(name="mc_dispatch_aborts")
dispatch_session_us = LatencyRecorder(name="mc_dispatch_session_us")

_method_counters: Dict[Tuple[str, str], Adder] = {}
_method_counters_lock = threading.Lock()


def _method_counter(service: str, method: str) -> Adder:
    """Per-kernel session counter (``mc_dispatch_<svc>_<m>_sessions``),
    minted on first use — the bvar registry keeps it scrapeable."""
    key = (service, method)
    with _method_counters_lock:
        ctr = _method_counters.get(key)
        if ctr is None:
            safe = "_".join(
                "".join(c if c.isalnum() else "_" for c in part)
                for part in key
            )
            ctr = Adder(name=f"mc_dispatch_{safe}_sessions")
            _method_counters[key] = ctr
        return ctr


# -- session fault plane -------------------------------------------------------
#
# A session is no longer fire-and-forget: every party (proposer included)
# registers it here with a deadline and an abort event.  Death of a party
# — detected from the proposer's failed run RPC, a dying control socket,
# or a device/mc link's fail() hook — aborts the session FABRIC-WIDE: an
# abort broadcast (phase:"abort") plus each party's own deadline watch
# makes every survivor exit the lockstep chain with a clean ESESSION
# instead of hanging in a barrier the dead party can never join.


class SessionAborted(RuntimeError):
    """A collective session aborted (party death, deadline, or reject).

    ``dead_indexes``/``survivor_indexes`` are party positions in the
    proposal's mesh order — the re-propose path runs the next session
    over exactly ``survivor_indexes``."""

    def __init__(
        self,
        reason: str,
        dead_indexes=(),
        survivor_indexes=(),
        rejects=(),
    ):
        super().__init__(reason)
        from incubator_brpc_tpu.utils.status import ErrorCode

        self.error_code = int(ErrorCode.ESESSION)
        self.reason = reason
        self.dead_indexes = tuple(dead_indexes)
        self.survivor_indexes = tuple(survivor_indexes)
        self.rejects = tuple(rejects)  # (index, error_text) non-death fails


class _SessionState:
    __slots__ = (
        "session_id", "party_ids", "owner", "deadline", "abort_event",
        "abort_reason", "aborted",
    )

    def __init__(self, session_id, party_ids, deadline, owner):
        self.session_id = session_id
        self.party_ids = tuple(party_ids)
        self.owner = owner  # the serving Server (None on the proposer)
        self.deadline = deadline  # absolute monotonic seconds (0 = none)
        self.abort_event = threading.Event()
        self.abort_reason = ""
        self.aborted = False


# session id -> every local registrant (proposer AND parties: in a
# single-controller run — and the in-process tests — several parties of
# ONE session live in one process; an abort must unwedge all of them)
_sessions: Dict[str, List[_SessionState]] = {}
_sessions_lock = threading.Lock()


def _register_session(session_id, party_ids, deadline, owner=None):
    st = _SessionState(session_id, party_ids, deadline, owner)
    with _sessions_lock:
        _sessions.setdefault(session_id, []).append(st)
    return st


def _unregister_session(st: _SessionState) -> None:
    with _sessions_lock:
        states = _sessions.get(st.session_id)
        if states is not None:
            try:
                states.remove(st)
            except ValueError:
                pass
            if not states:
                del _sessions[st.session_id]


def active_sessions(owner=None) -> int:
    """Live (registered, not yet closed) session registrations — all of
    them, or only those served by ``owner`` (Server.enter_lame_duck
    drains its own)."""
    with _sessions_lock:
        return sum(
            1
            for states in _sessions.values()
            for st in states
            if owner is None or st.owner is owner
        )


def abort_session(session_id: str, reason: str) -> bool:
    """Flip every local registrant of one session to aborted (idempotent;
    counted once per session per process). Returns False when the id is
    unknown — already closed or never registered here, both fine for a
    best-effort broadcast."""
    with _sessions_lock:
        states = list(_sessions.get(session_id, ()))
        if not states:
            return False
        first = any(not st.aborted for st in states)
        for st in states:
            st.aborted = True
            if not st.abort_reason:
                st.abort_reason = reason
    if first:
        dispatch_aborts << 1
        logger.warning("mc_dispatch session %s aborted: %s", session_id, reason)
    for st in states:
        st.abort_event.set()
    return True


def abort_sessions_for_devices(device_ids, reason: str) -> int:
    """Link-death feedback (transport/device_link fail() calls here): any
    active session with a party on one of these GLOBAL device ids aborts —
    the link that carried the lockstep traffic is gone, so the chain can
    never converge. Returns the number of sessions aborted."""
    dead = set(int(d) for d in device_ids)
    with _sessions_lock:
        hit = [
            sid for sid, states in _sessions.items()
            if any(dead & set(st.party_ids) for st in states)
        ]
    for sid in hit:
        abort_session(sid, reason)
    return len(hit)


# Between-step seam: chaos drills park parties here (deterministically
# mid-session) and production leaves it None.  Called as fn(step_index)
# before each lockstep step on every party running a registered session.
_step_hook: Optional[Callable] = None


def set_step_hook(fn: Optional[Callable]) -> None:
    global _step_hook
    _step_hook = fn


# -- kernel resolution ---------------------------------------------------------

# Fallback resolvers for builtin kernels that are minted per-geometry
# rather than registered by a Server (mc_collective's pmean installs one).
# Signature: (service, method, width_bytes) -> Optional[DeviceMethod].
_resolvers: List[Callable] = []


def register_method_resolver(fn: Callable) -> None:
    if fn not in _resolvers:
        _resolvers.append(fn)


def resolve_method(service: str, method: str, width: Optional[int] = None):
    """Resolve (service, method) to this process's DeviceMethod: the
    process-global registry first (what Server.add_service fills), then
    the builtin resolvers. ``width`` (row bytes) must match the resolved
    geometry — a session whose parties disagree on geometry could never
    exchange shards."""
    from incubator_brpc_tpu.rpc.device_method import lookup_device_method

    dm = lookup_device_method(service, method)
    if dm is None:
        for r in list(_resolvers):
            dm = r(service, method, width)
            if dm is not None:
                break
    if dm is None:
        return None
    if width is not None and dm.width != width:
        return None
    return dm


def _devices_by_id(ids: List[int]):
    import jax

    by_id = {d.id: d for d in jax.devices()}
    try:
        return [by_id[i] for i in ids]
    except KeyError as e:
        raise ValueError(
            f"device id {e} not in this process's global view "
            f"(is jax.distributed initialized everywhere?)"
        )


# -- the shared lockstep step --------------------------------------------------


_step_cache: Dict[tuple, tuple] = {}  # (fp, party ids) -> (step_fn, dm)
_step_cache_lock = threading.Lock()


def _make_step(dm, mesh, sharding, party_ids):
    """The identical jitted program every party dispatches: one shard_map
    application of the resolved kernel over the party axis. Axis name
    "par" matches the single-controller fused dispatch (rpc/combo.py), so
    axis-reducing kernels produce the same bytes on both planes. Cached
    per (kernel fingerprint, party set): the ParallelChannel lowering
    runs one session per combo CALL, and re-tracing every call would put
    XLA compilation on the request path (combo's _fused_cache, here)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from incubator_brpc_tpu.parallel.compat import shard_map_compat

    key = (dm.fingerprint(), tuple(party_ids))
    with _step_cache_lock:
        cached = _step_cache.get(key)
        if cached is not None and cached[1] is not dm:
            cached = None  # same name re-registered with a new DeviceMethod
        if cached is None:

            def body(data, ns):
                out, m = dm.kernel(data[0], ns[0])
                return out[None], m[None]

            wrapped = shard_map_compat(
                body, mesh=mesh, in_specs=(P("par"), P("par")),
                out_specs=(P("par"), P("par")),
            )
            cached = (
                jax.jit(wrapped, out_shardings=(sharding, sharding)), dm
            )
            _step_cache[key] = cached
    return cached[0]


def run_dispatch_session(
    party_ids: List[int],
    own_index: int,
    dm,
    operands: List[bytes],
    steps: int,
    service: str = "?",
    method: str = "?",
    should_abort: Optional[Callable[[], Optional[str]]] = None,
) -> Tuple[np.ndarray, int, float]:
    """Run this party's side of a K-step session of ``dm``'s kernel;
    returns (own final row, own final n, elapsed seconds). Every party
    calls this with identical arguments except ``own_index`` — the jitted
    programs must match or the collectives cannot rendezvous. Each party
    device-places the shards it can ADDRESS: in the multi-controller
    deployment that is exactly its own row (the peers' devices are
    visible but not addressable — they contribute their shards from their
    own processes); in a single-controller run one call owns every shard
    and the session degenerates to the full computation. Operands stay
    device-resident across the chain: only the initial device_put and the
    final fetch cross the host boundary, and XLA pipelines the K
    dispatches (the ack/credit discipline is the response barrier the
    proposer collects — no per-step coordination)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = _devices_by_id(party_ids)
    n = len(devices)
    if len(operands) != n:
        raise ValueError("one operand per party required")
    mesh = Mesh(np.asarray(devices), ("par",))
    sharding = NamedSharding(mesh, P("par"))
    step_fn = _make_step(dm, mesh, sharding, party_ids)

    addressable = sharding.addressable_devices
    own_dev = devices[own_index]
    if own_dev not in addressable:
        raise ValueError(
            f"party {own_index} device {own_dev} is not addressable from "
            f"this process"
        )
    row_shards, n_shards = [], []
    for i, dev in enumerate(devices):
        if dev not in addressable:
            continue
        row, nn = dm.pack(operands[i])
        row_shards.append(jax.device_put(row[None, :], dev))
        n_shards.append(
            jax.device_put(np.asarray([nn], dtype=np.int32), dev)
        )
    x = jax.make_array_from_single_device_arrays(
        (n, dm.width), sharding, row_shards
    )
    ns = jax.make_array_from_single_device_arrays((n,), sharding, n_shards)
    t0 = time.perf_counter()
    for step_i in range(steps):
        # fault plane: an aborted session exits the chain HERE, between
        # dispatches, with a clean ESESSION — dispatches are async (XLA
        # pipelines them), so the check costs nothing and the party never
        # enters a barrier its dead peer cannot join.  A party already
        # blocked INSIDE one collective finishes that step first (or hits
        # the runtime's own collective timeout) — the between-step check
        # plus every party's deadline watch is what bounds the hang.
        if should_abort is not None:
            why = should_abort()
            if why:
                raise SessionAborted(why)
        hook = _step_hook
        if hook is not None:
            hook(step_i)  # chaos-drill seam (None in production)
        x, ns = step_fn(x, ns)  # chained: operands never leave the devices
    if should_abort is not None:
        # last look before the blocking fetch: the final collect is the
        # one host-blocking point of the chain
        why = should_abort()
        if why:
            raise SessionAborted(why)
    own_row = own_n = None
    for s in x.addressable_shards:
        # a process can address several mesh devices (single-controller
        # runs): OUR shard is the one on devices[own_index]
        if s.device == own_dev:
            own_row = np.asarray(s.data).reshape(-1)
    for s in ns.addressable_shards:
        if s.device == own_dev:
            own_n = int(np.asarray(s.data).reshape(-1)[0])
    elapsed = time.perf_counter() - t0
    assert own_row is not None and own_n is not None
    dispatch_sessions << 1
    dispatch_steps << steps
    dispatch_session_us << elapsed * 1e6
    _method_counter(service, method) << 1
    return own_row, own_n, elapsed


# -- rpcz spans (annotated with method identity) -------------------------------


def _start_session_span(
    service: str,
    method: str,
    fingerprint: str,
    party_ids: List[int],
    own_index: int,
    steps: int,
    trace_id: int = 0,
    parent_span_id: int = 0,
):
    from incubator_brpc_tpu.builtin.rpcz import (
        SPAN_TYPE_COLLECTIVE,
        start_custom_span,
    )

    span = start_custom_span(
        SPAN_TYPE_COLLECTIVE,
        service,
        method,
        trace_id=trace_id,
        parent_span_id=parent_span_id,
    )
    if span is not None:
        span.annotate(
            f"method={service}.{method} fingerprint={fingerprint} "
            f"steps={steps} index={own_index} parties={party_ids}"
        )
    return span


def _end_session_span(span, error_code: int = 0) -> None:
    from incubator_brpc_tpu.builtin.rpcz import end_custom_span

    end_custom_span(span, error_code=error_code)


# -- server half ---------------------------------------------------------------


def _validate_proposal(req: dict):
    """Shared accept/run admission: bounds, then kernel identity. Returns
    (party_ids, own_index, steps, dm, err) where err is (code, text) on
    rejection — the clean control-stream reject that keeps a divergent
    party out of lockstep."""
    from incubator_brpc_tpu.utils.status import ErrorCode

    try:
        party_ids = [int(i) for i in req["parties"]]
        own_index = int(req["index"])
        steps = int(req["steps"])
        width = int(req["width"])
        service = str(req["service"])
        method = str(req["method"])
        fingerprint = str(req["fingerprint"])
    except (ValueError, KeyError, TypeError) as e:
        return None, None, None, None, (
            ErrorCode.EREQUEST, f"bad dispatch proposal: {e}"
        )
    if not (
        0 < steps <= MAX_STEPS
        and 0 < width <= MAX_WIDTH
        and 1 < len(party_ids) <= MAX_PARTIES
        and 0 <= own_index < len(party_ids)
        and len(set(party_ids)) == len(party_ids)
    ):
        return None, None, None, None, (
            ErrorCode.EREQUEST, "dispatch proposal out of bounds"
        )
    dm = resolve_method(service, method, width)
    if dm is None:
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.ENOMETHOD,
            f"no device method {service}.{method} with width {width} "
            f"registered in this process",
        )
    ours = dm.fingerprint()
    if ours != fingerprint:
        # same name, different kernel: entering lockstep would run a
        # program the proposer never named — reject before any dispatch
        dispatch_rejects << 1
        return None, None, None, None, (
            ErrorCode.EREQUEST,
            f"device method fingerprint mismatch for {service}.{method}: "
            f"proposal {fingerprint} vs local {ours}",
        )
    try:
        _devices_by_id(party_ids)
    except ValueError as e:
        return None, None, None, None, (ErrorCode.EREQUEST, str(e))
    return party_ids, own_index, steps, dm, None


def make_dispatch_handler(server):
    """Server half of ``_tpu_transport.collective_dispatch``: validate a
    session proposal against the local registry (accept phase — nothing
    runs), or bind the resolved kernel and run this party's side of the
    lockstep chain (run phase), answering with the final shard."""

    def collective_dispatch(cntl, request: bytes) -> bytes:
        try:
            req = json.loads(request.decode())
        except ValueError as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"undecodable proposal: {e}")
            return b""
        if req.get("phase") == "abort":
            # the abort broadcast: validated as little as possible — a
            # survivor must unwedge even when the rest of the proposal
            # state is unreachable or corrupt
            sid = str(req.get("session_id", ""))
            found = bool(sid) and abort_session(
                sid, str(req.get("reason", "")) or "aborted by proposer"
            )
            return json.dumps({"aborted": found}).encode()
        party_ids, own_index, steps, dm, err = _validate_proposal(req)
        if err is not None:
            cntl.set_failed(*err)
            return b""
        service, method = str(req["service"]), str(req["method"])
        floor = int(get_flag("mc_dispatch_min_steps"))
        if req.get("phase") != "accept" and steps < floor:
            # the accept ack raised our target to the floor; a run
            # proposal below it means the proposer did not fold this
            # party's target — reject rather than silently dispatch a
            # count the accept never agreed to (the close-barrier echo
            # below only proves the VALIDATED count was run)
            from incubator_brpc_tpu.utils.status import ErrorCode

            dispatch_rejects << 1
            cntl.set_failed(
                ErrorCode.EREQUEST,
                f"run proposal steps {steps} below this party's accepted "
                f"floor {floor}",
            )
            return b""
        if req.get("phase") == "accept":
            # Nothing is run or reserved; ``target`` lets this party RAISE
            # the step count (mc_dispatch_min_steps — e.g. a pipeline-depth
            # floor). The proposer folds every target with max — the
            # 2-party close dance's max(targets) join, generalized to N.
            target = min(
                max(steps, int(get_flag("mc_dispatch_min_steps"))), MAX_STEPS
            )
            return json.dumps(
                {"accept": True, "index": own_index, "target": target}
            ).encode()
        try:
            operands = [
                base64.b64decode(op) for op in req.get("operands", [])
            ]
            if len(operands) != len(party_ids):
                raise ValueError("one operand per party required")
            for op in operands:
                if len(op) > dm.width:
                    raise ValueError(
                        f"operand of {len(op)}B exceeds width {dm.width}"
                    )
        except (ValueError, TypeError) as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"bad operands: {e}")
            return b""
        # fault plane: a session_id-carrying run registers here so the
        # abort broadcast, the party's own deadline watch, link-death
        # feedback, and the proposer's control socket dying can all
        # unwedge this party mid-chain with a clean ESESSION
        session_id = str(req.get("session_id", "")) or None
        st = None
        sock_hook = None
        if session_id is not None:
            deadline_ms = float(req.get("deadline_ms", 0) or 0)
            if deadline_ms <= 0:
                deadline_ms = float(get_flag("mc_dispatch_session_deadline_ms"))
            deadline = (
                time.monotonic() + deadline_ms / 1000.0 if deadline_ms > 0
                else 0.0
            )
            st = _register_session(
                session_id, party_ids, deadline, owner=server
            )
            sock = getattr(cntl, "_sock", None)
            hooks = getattr(sock, "on_failed", None)
            if hooks is not None:
                # the proposer died with us mid-chain: its control
                # connection failing IS the death signal (socket feedback)
                def _proposer_died(_s, _sid=session_id):
                    abort_session(_sid, "proposer connection died mid-session")

                hooks.append(_proposer_died)
                sock_hook = (hooks, _proposer_died)

        def _should_abort():
            if st is None:
                return None
            if st.abort_event.is_set():
                return st.abort_reason or "session aborted"
            if st.deadline and time.monotonic() > st.deadline:
                abort_session(st.session_id, "session deadline exceeded")
                return "session deadline exceeded"
            return None

        span = _start_session_span(
            service, method, dm.fingerprint(), party_ids, own_index, steps,
            trace_id=cntl.trace_id, parent_span_id=cntl.span_id,
        )
        try:
            own_row, own_n, elapsed = run_dispatch_session(
                party_ids, own_index, dm, operands, steps,
                service=service, method=method, should_abort=_should_abort,
            )
        except SessionAborted as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            _end_session_span(span, error_code=ErrorCode.ESESSION)
            cntl.set_failed(ErrorCode.ESESSION, f"session aborted: {e.reason}")
            return b""
        except Exception as e:
            dispatch_errors << 1
            from incubator_brpc_tpu.utils.status import ErrorCode

            _end_session_span(span, error_code=ErrorCode.EINTERNAL)
            logger.exception("dispatch session failed")
            cntl.set_failed(ErrorCode.EINTERNAL, f"dispatch session: {e!r}")
            return b""
        finally:
            if sock_hook is not None:
                try:
                    sock_hook[0].remove(sock_hook[1])
                except ValueError:
                    pass
            if st is not None:
                _unregister_session(st)
        _end_session_span(span)
        return json.dumps(
            {
                "result": base64.b64encode(
                    dm.unpack(own_row, own_n)
                ).decode(),
                "steps": steps,
                "elapsed_s": elapsed,
                "index": own_index,
            }
        ).encode()

    return collective_dispatch


# -- client half: the N-party session scheduler --------------------------------


def propose_dispatch(
    channels,
    party_ids: List[int],
    service: str,
    method: str,
    operands: List[bytes],
    steps: int = 1,
    proposer_index: Optional[int] = None,
    timeout_ms: float = 120000,
    session_deadline_ms: Optional[float] = None,
) -> dict:
    """Schedule an N-party session of a registered device method.

    ``party_ids`` are global device ids in mesh order; ``operands[i]`` is
    party i's initial row. ``channels[j]`` is a host channel to the
    server playing the j-th REMOTE party index (every index except
    ``proposer_index``; with ``proposer_index=None`` the proposer is a
    pure scheduler and every party is remote — the ParallelChannel
    lowering's shape). Returns ``{"results": [bytes per party],
    "final_steps": k, "elapsed_s": proposer's chain seconds or None}``.

    Three phases over the star:
    1. accept fan-out + barrier — every party resolves the (service,
       method) pair locally and fingerprint-checks it; any reject
       surfaces HERE, before lockstep. ``final = max(all targets)``.
    2. run fan-out (async — every party must be dispatching before any
       can finish) under a fault watcher, then the proposer's own chain
       if it participates.
    3. completion barrier — every response must echo ``final`` (the
       convergent close: all parties dispatched exactly the same count).

    Fault semantics: the run phase registers a SESSION (random id +
    ``session_deadline_ms`` budget, default the RPC timeout) on every
    party.  The watcher classifies a failed run RPC: connectivity
    failures (dead party) and rejects both trigger an ABORT — an abort
    broadcast to every surviving party plus the local abort event — so
    every survivor exits its lockstep chain with ESESSION instead of
    hanging in a barrier; :class:`SessionAborted` then carries the dead
    and surviving index sets for the re-propose path
    (:func:`propose_with_recovery`).  Breaker feedback is charged to the
    dead party only: the survivors' ESESSION answers are excluded from
    error cost by the LB (lb/__init__._feed_breaker).
    """
    import threading as _threading

    from incubator_brpc_tpu.rpc.controller import Controller
    from incubator_brpc_tpu.transport.device_link import HANDSHAKE_SERVICE

    n = len(party_ids)
    remote_indexes = [i for i in range(n) if i != proposer_index]
    if len(remote_indexes) != len(channels):
        raise ValueError("one channel per remote party required")
    if len(operands) != n:
        raise ValueError("one operand per party required")
    dm = resolve_method(service, method)
    if dm is None:
        raise LookupError(
            f"device method {service}.{method} not registered locally "
            f"(the proposer validates against its own registry too)"
        )
    fingerprint = dm.fingerprint()
    for op in operands:
        if len(op) > dm.width:
            raise ValueError(
                f"operand of {len(op)}B exceeds method width {dm.width}"
            )

    # session identity + deadline: what the fault plane keys on.  Every
    # party gets the SAME budget, measured from its own clock at proposal
    # arrival — a partitioned party that never hears the abort broadcast
    # still unwedges at its own deadline.
    import uuid

    session_id = uuid.uuid4().hex
    sess_ms = (
        float(session_deadline_ms)
        if session_deadline_ms and session_deadline_ms > 0
        else float(get_flag("mc_dispatch_session_deadline_ms"))
        or float(timeout_ms)
    )

    def proposal(idx: int, nsteps: int, phase: str = "") -> bytes:
        d = {
            "parties": party_ids,
            "index": idx,
            "steps": nsteps,
            "width": dm.width,
            "service": service,
            "method": method,
            "fingerprint": fingerprint,
        }
        if phase:
            d["phase"] = phase
        else:
            # the FULL operand list: each party device-places only the
            # shards it can address (its own, in the mc deployment), but
            # a single-controller party owns every shard and needs them
            d["operands"] = [
                base64.b64encode(op).decode() for op in operands
            ]
            d["session_id"] = session_id
            d["deadline_ms"] = sess_ms
        return json.dumps(d).encode()

    def _call(ch, payload):
        cntl = Controller(timeout_ms=timeout_ms)
        cntl._force_host = True  # scheduling rides the host plane
        ev = _threading.Event()
        ch.call_method(
            HANDSHAKE_SERVICE,
            DISPATCH_METHOD,
            payload,
            cntl=cntl,
            done=lambda c, _ev=ev: _ev.set(),
        )
        return cntl, ev

    # Phase 1 — accept barrier + the monotone-max step-count join
    accepts = [
        _call(ch, proposal(idx, steps, phase="accept"))
        for ch, idx in zip(channels, remote_indexes)
    ]
    deadline = time.monotonic() + timeout_ms / 1000.0
    final = steps
    for cntl, ev in accepts:
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            raise TimeoutError("dispatch peer never acknowledged proposal")
        if cntl.failed():
            raise RuntimeError(
                f"dispatch proposal rejected: {cntl.error_text}"
            )
        ack = json.loads(cntl.response_payload.decode())
        final = max(final, int(ack.get("target", steps)))

    # Phase 2 — run fan-out (async: a sync proposal would deadlock — the
    # first party's collective blocks on parties never told to start)
    pending = [
        _call(ch, proposal(idx, final))
        for ch, idx in zip(channels, remote_indexes)
    ]
    from incubator_brpc_tpu.utils.status import ErrorCode

    # connectivity-class failures of a RUN rpc = the party is DEAD for
    # this session (its chain will never converge); anything else is a
    # reject.  Both abort the session — only death feeds the re-propose
    # path's survivor set.
    _DEATH_CODES = frozenset(
        {
            ErrorCode.EFAILEDSOCKET, ErrorCode.EEOF, ErrorCode.ECLOSE,
            ErrorCode.EHOSTDOWN, ErrorCode.ERPCTIMEDOUT, ErrorCode.ELOGOFF,
            ErrorCode.ETIMEDOUT,
        }
    )
    session_deadline = time.monotonic() + sess_ms / 1000.0
    st = _register_session(session_id, party_ids, session_deadline)
    outcome = {"dead": [], "rejects": [], "reason": ""}
    watch_stop = _threading.Event()

    def _broadcast_abort(reason: str, skip) -> None:
        """phase:"abort" to every party not already known dead (async,
        best-effort — each party's own deadline is the backstop)."""
        msg = json.dumps(
            {"phase": "abort", "session_id": session_id, "reason": reason}
        ).encode()
        for ch, idx in zip(channels, remote_indexes):
            if idx in skip:
                continue
            try:
                _call(ch, msg)
            except Exception:
                logger.exception("abort broadcast to party %d failed", idx)

    broadcast_done = [False]

    def _trigger_abort(reason: str) -> None:
        outcome["reason"] = outcome["reason"] or reason
        if not broadcast_done[0]:
            # one broadcast per session: later classifications (a second
            # death found while the first abort settles) add to the
            # outcome but the survivors were already told
            broadcast_done[0] = True
            _broadcast_abort(reason, set(outcome["dead"]))
        abort_session(session_id, reason)

    def _watch() -> None:
        # the generalized rejection watch (supersedes the old fixed-50 ms
        # participating-proposer scan): classify every settled run RPC as
        # it lands; on the FIRST death/reject — or the session deadline —
        # abort fabric-wide so survivors (the proposer's own chain
        # included) exit their lockstep loops instead of waiting in a
        # barrier the dead party can never join.  After an abort the
        # watcher KEEPS scanning until every run RPC settles (or the
        # deadline): an ESESSION answer is a SURVIVOR reporting the abort
        # (its link saw the death first, or our broadcast arrived) — not
        # a reject, and never the dead party, which must still be
        # identified for the re-propose path.
        seen = set()
        while not watch_stop.wait(0.01):
            done = True
            now = time.monotonic()
            for (cntl, ev), idx in zip(pending, remote_indexes):
                if not ev.is_set():
                    done = False
                    continue
                if idx in seen or not cntl.failed():
                    continue
                seen.add(idx)
                code = cntl.error_code
                if code == ErrorCode.ESESSION:
                    # cooperative abort report from a LIVING party:
                    # propagate (covers the link-death-detected-remotely
                    # ordering) but blame nobody
                    _trigger_abort(
                        f"party {idx} reported abort: {cntl.error_text}"
                    )
                elif code in _DEATH_CODES:
                    outcome["dead"].append(idx)
                    _trigger_abort(
                        f"party {idx} died mid-session: {cntl.error_text}"
                    )
                else:
                    outcome["rejects"].append((idx, cntl.error_text))
                    _trigger_abort(
                        f"party {idx} rejected the run: {cntl.error_text}"
                    )
            if done:
                return
            if st.abort_event.is_set() and not broadcast_done[0]:
                # aborted from OUTSIDE the rpc plane (the proposer's own
                # link-death hook fired): the survivors still need the
                # broadcast — their links may be fine
                _trigger_abort(st.abort_reason or "session aborted")
            if now > session_deadline:
                _trigger_abort("session deadline exceeded")
                return

    watcher = _threading.Thread(
        target=_watch, name="mc-session-watch", daemon=True
    )
    watcher.start()

    own_elapsed = None
    results: List[Optional[bytes]] = [None] * n
    abort_exc: Optional[SessionAborted] = None
    try:
        if proposer_index is not None:

            def _own_should_abort():
                if st.abort_event.is_set():
                    return st.abort_reason or "session aborted"
                if time.monotonic() > session_deadline:
                    abort_session(session_id, "session deadline exceeded")
                    return "session deadline exceeded"
                return None

            span = _start_session_span(
                service, method, fingerprint, party_ids, proposer_index,
                final,
            )
            try:
                own_row, own_n, own_elapsed = run_dispatch_session(
                    party_ids, proposer_index, dm, operands,
                    final, service=service, method=method,
                    should_abort=_own_should_abort,
                )
            except SessionAborted as e:
                _end_session_span(span, error_code=ErrorCode.ESESSION)
                abort_exc = e
            except Exception:
                dispatch_errors << 1
                _end_session_span(span, error_code=ErrorCode.EINTERNAL)
                # our own chain failed: the peers' chains can never
                # converge either — take the whole session down cleanly
                _trigger_abort("proposer chain failed")
                raise
            else:
                _end_session_span(span)
                results[proposer_index] = dm.unpack(own_row, own_n)

        # Phase 3 — completion barrier; the watcher exits once every run
        # RPC settled, or as soon as it aborted the session
        watcher.join()
        if st.abort_event.is_set() or abort_exc is not None:
            dead = sorted(set(outcome["dead"]))
            survivors = [i for i in range(n) if i not in set(dead)]
            reason = (
                outcome["reason"]
                or (abort_exc.reason if abort_exc is not None else "")
                or st.abort_reason
                or "session aborted"
            )
            raise SessionAborted(
                reason,
                dead_indexes=dead,
                survivor_indexes=survivors,
                rejects=outcome["rejects"],
            )
        for (cntl, ev), idx in zip(pending, remote_indexes):
            if cntl.failed():  # defensive: the watcher classifies these
                raise RuntimeError(
                    f"dispatch peer failed: {cntl.error_text}"
                )
            resp = json.loads(cntl.response_payload.decode())
            # each party echoes the count it validated AND ran (a proposal
            # below the party's accepted floor is rejected, never silently
            # re-counted) — a mismatch here means a corrupted or stale
            # proposal reached that party
            if int(resp.get("steps", -1)) != final:
                raise RuntimeError(
                    f"party {idx} dispatched {resp.get('steps')} steps, "
                    f"agreed final was {final} — close did not converge"
                )
            results[idx] = base64.b64decode(resp["result"])
    finally:
        watch_stop.set()
        _unregister_session(st)
    return {"results": results, "final_steps": final, "elapsed_s": own_elapsed}


def propose_with_recovery(
    channels,
    party_ids: List[int],
    service: str,
    method: str,
    operands: List[bytes],
    steps: int = 1,
    proposer_index: Optional[int] = None,
    timeout_ms: float = 120000,
    session_deadline_ms: Optional[float] = None,
    max_reproposals: int = 1,
) -> dict:
    """:func:`propose_dispatch` with the re-propose path: a session that
    aborts on PARTY DEATH is re-proposed over the surviving party set (up
    to ``max_reproposals`` times).  Rejects and proposer death are not
    recoverable this way and re-raise.  The result dict gains
    ``dead_party_ids`` (global device ids dropped along the way, [] on a
    clean first run)."""
    chs = list(channels)
    pids = list(party_ids)
    ops = list(operands)
    pidx = proposer_index
    dropped: List[int] = []
    for attempt in range(max_reproposals + 1):
        remote = [i for i in range(len(pids)) if i != pidx]
        try:
            out = propose_dispatch(
                chs, pids, service, method, ops, steps=steps,
                proposer_index=pidx, timeout_ms=timeout_ms,
                session_deadline_ms=session_deadline_ms,
            )
            out["dead_party_ids"] = dropped
            return out
        except SessionAborted as e:
            dead = set(e.dead_indexes)
            if (
                attempt == max_reproposals
                or not dead
                or e.rejects
                or (pidx is not None and pidx in dead)
                or len(pids) - len(dead) < 2
            ):
                raise
            dropped.extend(pids[i] for i in sorted(dead))
            logger.warning(
                "re-proposing %s.%s over %d survivor(s) after: %s",
                service, method, len(pids) - len(dead), e.reason,
            )
            keep = [i for i in range(len(pids)) if i not in dead]
            chs = [
                ch for ch, idx in zip(chs, remote) if idx not in dead
            ]
            ops = [ops[i] for i in keep]
            pids = [pids[i] for i in keep]
            if pidx is not None:
                pidx = keep.index(pidx)
    raise AssertionError("unreachable")


# -- the ParallelChannel lowering ----------------------------------------------

mc_lowered_dispatches = Adder(name="parallel_channel_mc_lowered")


def lower_parallel_call(
    channels,
    devices,
    service: str,
    method: str,
    requests: List[bytes],
    timeout_ms: float,
) -> List[bytes]:
    """One combo call lowered onto the method plane: the sub-channels'
    server devices form the party axis (channel order — the same order
    the single-controller fused dispatch stacks, so merges are
    byte-identical), each party's operand is its sub-request, the
    proposer is a pure scheduler (its process cannot address any party
    device), and one 1-step session replaces the host fan-out. Returns
    per-sub response bytes in channel order."""
    if not timeout_ms or timeout_ms <= 0:
        timeout_ms = 120000.0
    out = propose_dispatch(
        channels,
        [d.id for d in devices],
        service,
        method,
        requests,
        steps=1,
        proposer_index=None,
        timeout_ms=timeout_ms,
    )
    mc_lowered_dispatches << 1
    return out["results"]
