"""Pipelined cross-process collective sessions — RPC-scheduled, ICI-run.

The combo-channel fusion (rpc/combo.py + parallel/collective.py) collapses
a ParallelChannel call into ONE shard_map dispatch — but only inside one
controller, where the client stages every party's operand itself. Across
controllers a one-shot fused call cannot win: the client cannot place
bytes on non-addressable devices, so the operands would ride the host
plane anyway (docs/DEVICE_PLANE.md). What DOES win across processes is
the PIPELINED shape: schedule once over the host plane, then run K
lockstep collective steps whose operands never leave the devices — the
steady-state of the reference's "RDMA for tensor traffic" story, and of
every real multi-host training loop.

A session is proposed as a plain RPC to every server
(``_tpu_transport.collective``): {parties (global device ids), your
party index, steps, width, seed}. Each party — client included — then
runs the IDENTICAL jitted program: K chained ``shard_map`` steps over
``Mesh(parties, ("party",))`` where each step exchanges shards with a
collective (``pmean`` here: every party's operand converges to the
global mean, which makes convergence a checkable invariant). Lockstep
needs no per-step coordination: the step count was agreed up front, the
chain is data-dependent, and XLA pipelines the K dispatches.

Deployment contract: every party is one process of a ``jax.distributed``
group (the mc_link deployment); the session only needs the group — no
device link is required, though sessions and links share the group
freely (mc_worker's fabric client runs both).
"""

from __future__ import annotations

import json
import logging
import time
from typing import List, Tuple

import numpy as np

from incubator_brpc_tpu.bvar import Adder, LatencyRecorder

logger = logging.getLogger(__name__)

COLLECTIVE_METHOD = "collective"

# How long propose_collective watches freshly-dispatched RUN proposals for
# an instant bounce (admission ELIMIT from an overlapping session, a server
# mid-stop) before entering its own session. The accept pre-ack already
# covers validation rejections, so this only needs to span a local RPC
# round trip — 10x under the old fixed 0.5 s grace window.
_REJECT_WATCH_S = 0.05

# session-level observability (ISSUE: the collective plane was blind):
# every run_collective_session — proposer and server parties alike —
# counts here and, when rpcz samples it, leaves one span in the proposing
# RPC's trace carrying step count / operand width / participant set
collective_sessions = Adder(name="mc_collective_sessions")
collective_steps = Adder(name="mc_collective_steps")
collective_errors = Adder(name="mc_collective_errors")
collective_session_us = LatencyRecorder(name="mc_collective_session_us")


def _start_session_span(
    party_ids: List[int],
    own_index: int,
    steps: int,
    width: int,
    trace_id: int = 0,
    parent_span_id: int = 0,
):
    from incubator_brpc_tpu.builtin.rpcz import (
        SPAN_TYPE_COLLECTIVE,
        start_custom_span,
    )

    span = start_custom_span(
        SPAN_TYPE_COLLECTIVE,
        "_tpu_transport",
        COLLECTIVE_METHOD,
        trace_id=trace_id,
        parent_span_id=parent_span_id,
    )
    if span is not None:
        span.annotate(
            f"steps={steps} width={width} index={own_index} "
            f"parties={party_ids}"
        )
    return span


def _end_session_span(span, error_code: int = 0) -> None:
    from incubator_brpc_tpu.builtin.rpcz import end_custom_span

    end_custom_span(span, error_code=error_code)


def _run_observed_session(span, party_ids, own_index, steps, width, seed):
    """run_collective_session under span/counter bookkeeping: a raise
    counts one error and closes the span with EINTERNAL (shared by the
    handler and proposer parties); the SUCCESS close stays with the
    caller, which may have more to do before the span ends."""
    try:
        return run_collective_session(party_ids, own_index, steps, width, seed)
    except Exception:
        collective_errors << 1
        from incubator_brpc_tpu.utils.status import ErrorCode

        _end_session_span(span, error_code=ErrorCode.EINTERNAL)
        raise


def _devices_by_id(ids: List[int]):
    from incubator_brpc_tpu.parallel.mc_dispatch import (
        _devices_by_id as _impl,
    )

    return _impl(ids)


# -- pmean as ONE registered method on the collective method plane -------------
#
# The session machinery itself lives in parallel/mc_dispatch.py and is
# kernel-agnostic: a session names a registered device method and every
# party fingerprint-validates it before entering lockstep. pmean — the
# original canned demo — survives as just one such method: the kernel
# below reinterprets the row bytes as float32, pmeans over the party
# axis, and writes the bytes back. It is width-independent (geometry is
# the DeviceMethod's), so one source mints a DeviceMethod per requested
# width via the resolver — identical fingerprints in every process that
# imports this module.

PMEAN_SERVICE = "_collective"
PMEAN_METHOD = "pmean"


def _pmean_bytes_kernel(data, n):
    import jax
    import jax.numpy as jnp

    f = jax.lax.bitcast_convert_type(data.reshape(-1, 4), jnp.float32)
    m = jax.lax.pmean(f, "par")
    return jax.lax.bitcast_convert_type(m, jnp.uint8).reshape(-1), n


_pmean_dms: dict = {}
_pmean_lock = __import__("threading").Lock()


def _pmean_dm(width_bytes: int):
    from incubator_brpc_tpu.parallel import quantized as _quantized
    from incubator_brpc_tpu.rpc.device_method import DeviceMethod

    with _pmean_lock:
        dm = _pmean_dms.get(width_bytes)
        if dm is None:
            # chunkable: pmean is elementwise along the width (psum of a
            # slice IS the slice of the psum) and passes n through — the
            # chunk-safety contract verbatim (the declaration is a
            # capability, not kernel identity: fingerprints unchanged)
            dm = DeviceMethod(
                _pmean_bytes_kernel, width=width_bytes, chunkable=True
            )
            # the quantize= session knob resolves through these variants
            # (block-aligned widths only; others reject pre-lockstep)
            _quantized.attach_pmean_variants(dm, width_bytes)
            _pmean_dms[width_bytes] = dm
        return dm


def _resolve_pmean(service: str, method: str, width):
    """mc_dispatch method resolver: mints the pmean DeviceMethod for any
    float32-aligned width, so sessions of arbitrary geometry resolve the
    same fingerprint everywhere without a Server registration."""
    if (
        service == PMEAN_SERVICE
        and method == PMEAN_METHOD
        and isinstance(width, int)
        and width > 0
        and width % 4 == 0
    ):
        return _pmean_dm(width)
    return None


def _install_resolver() -> None:
    from incubator_brpc_tpu.parallel import mc_dispatch

    mc_dispatch.register_method_resolver(_resolve_pmean)


_install_resolver()


def run_collective_session(
    party_ids: List[int],
    own_index: int,
    steps: int,
    width: int,
    seed: int,
) -> Tuple[np.ndarray, float]:
    """Run this party's half of the session; returns (final own shard,
    elapsed seconds). Every party calls this with identical arguments
    except ``own_index`` — the programs must match or the collectives
    cannot rendezvous. Since the collective method plane landed this is a
    thin float32 veneer over ``mc_dispatch.run_dispatch_session`` with
    the registered pmean method: one step pulls every party toward the
    global mean, the invariant each party verifies independently."""
    from incubator_brpc_tpu.parallel.mc_dispatch import run_dispatch_session

    dm = _pmean_dm(4 * width)
    # every party's operand derives from the seed, so each side can stage
    # whatever shards it addresses without communication (exactly its own
    # row in the mc deployment; all rows in a single-controller run)
    operands = [
        _party_operand(seed, i, width).tobytes()
        for i in range(len(party_ids))
    ]
    own_row, own_n, elapsed = run_dispatch_session(
        party_ids, own_index, dm, operands, steps,
        service=PMEAN_SERVICE, method=PMEAN_METHOD,
    )
    own = np.frombuffer(
        bytes(np.asarray(own_row[:own_n], dtype=np.uint8)), dtype=np.float32
    ).copy()
    collective_sessions << 1
    collective_steps << steps
    collective_session_us << elapsed * 1e6
    return own, elapsed


def _party_operand(seed: int, index: int, width: int) -> np.ndarray:
    rng = np.random.default_rng(seed + index)
    return rng.standard_normal(width).astype(np.float32)


def expected_mean(seed: int, nparties: int, width: int) -> np.ndarray:
    return np.mean(
        [_party_operand(seed, i, width) for i in range(nparties)], axis=0
    )


def make_collective_handler(server):
    """Server half: accept a session proposal, run our party's program on
    a worker fiber, answer with the final shard's checksum once the chain
    drains (the response doubles as the completion barrier the client
    collects)."""

    def collective(cntl, request: bytes) -> bytes:
        try:
            req = json.loads(request.decode())
            party_ids = [int(i) for i in req["parties"]]
            own_index = int(req["index"])
            steps = int(req["steps"])
            width = int(req["width"])
            seed = int(req["seed"])
        except (ValueError, KeyError, TypeError) as e:
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(ErrorCode.EREQUEST, f"bad collective proposal: {e}")
            return b""
        if not (0 < steps <= 100_000 and 0 < width <= (1 << 20)):
            from incubator_brpc_tpu.utils.status import ErrorCode

            cntl.set_failed(
                ErrorCode.EREQUEST, "collective proposal out of bounds"
            )
            return b""
        if req.get("phase") == "accept":
            # Accept pre-ack (ADVICE r5): the proposer waits for every
            # party's explicit accept BEFORE entering its own session,
            # instead of burning a fixed grace window. Validation beyond
            # the bounds above: every named device must be addressable in
            # this process's global view, or the session could never
            # rendezvous. Nothing is run or reserved here.
            try:
                _devices_by_id(party_ids)
            except ValueError as e:
                from incubator_brpc_tpu.utils.status import ErrorCode

                cntl.set_failed(ErrorCode.EREQUEST, str(e))
                return b""
            return json.dumps({"accept": True, "index": own_index}).encode()
        # the session span lands in the PROPOSING client's trace: the
        # trace/span ids arrived in the request meta (baidu_std-style
        # Dapper propagation) and are already on the controller
        span = _start_session_span(
            party_ids, own_index, steps, width,
            trace_id=cntl.trace_id, parent_span_id=cntl.span_id,
        )
        # Liveness: a party that never joins stalls the rendezvous until
        # the collective backend's own timeout errors the chain (gloo on
        # the CPU fabric; the coordination service reports dead PROCESSES
        # group-wide) — the raise lands here and answers EINTERNAL. A
        # live-but-declining peer is caught on the client by the accept
        # pre-ack phase in propose_collective.
        own, elapsed = _run_observed_session(
            span, party_ids, own_index, steps, width, seed
        )
        _end_session_span(span)
        return json.dumps(
            {
                "checksum": float(np.sum(own, dtype=np.float64)),
                "elapsed_s": elapsed,
                "steps": steps,
            }
        ).encode()

    return collective


def propose_collective(
    channels,
    party_ids: List[int],
    client_index: int,
    steps: int,
    width: int,
    seed: int,
    timeout_ms: float = 120000,
):
    """Client half: propose the session to every server (async — they
    must all start dispatching, the collective needs every party), run
    our own party's program, then collect completions. Returns
    {"own": shard, "elapsed_s": s, "server_checksums": [...]}.

    ``channels[i]`` is an initialized host channel to the server playing
    party ``server_indexes[i]``; party indexes are assigned positionally:
    servers take every index except ``client_index``."""
    import threading

    from incubator_brpc_tpu.rpc.controller import Controller
    from incubator_brpc_tpu.transport.device_link import HANDSHAKE_SERVICE

    server_indexes = [i for i in range(len(party_ids)) if i != client_index]
    if len(server_indexes) != len(channels):
        raise ValueError("one channel per server party required")

    def proposal(idx: int, phase: str = "") -> bytes:
        d = {
            "parties": party_ids,
            "index": idx,
            "steps": steps,
            "width": width,
            "seed": seed,
        }
        if phase:
            d["phase"] = phase
        return json.dumps(d).encode()

    # Phase 1 — explicit accept pre-ack from EVERY server (replaces the
    # old fixed 0.5 s grace window, ADVICE r5): each party validates the
    # proposal (fields, bounds, device visibility) and answers
    # immediately, without running anything. A rejection surfaces here,
    # BEFORE we enter our own session whose collective would wait on a
    # party that never joins — and a clean accept set lets us proceed the
    # moment the last ack lands instead of always burning 500 ms.
    accepts = []
    for ch, idx in zip(channels, server_indexes):
        cntl = Controller(timeout_ms=timeout_ms)
        ev = threading.Event()
        ch.call_method(
            HANDSHAKE_SERVICE,
            COLLECTIVE_METHOD,
            proposal(idx, phase="accept"),
            cntl=cntl,
            done=lambda c, _ev=ev: _ev.set(),
        )
        accepts.append((cntl, ev))
    accept_deadline = time.monotonic() + timeout_ms / 1000.0
    for cntl, ev in accepts:
        if not ev.wait(max(0.0, accept_deadline - time.monotonic())):
            raise TimeoutError("collective peer never acknowledged proposal")
        if cntl.failed():
            raise RuntimeError(
                f"collective proposal rejected: {cntl.error_text}"
            )

    # Phase 2 — the run proposals (async: every party must be dispatching
    # before any can finish; a sync proposal to server A would deadlock —
    # A's collective blocks on parties that were never told to start).
    # Mid-session process death stays the backend's liveness domain (the
    # coordination service / gloo timeout errors the chain group-wide).
    pending = []
    for ch, idx in zip(channels, server_indexes):
        cntl = Controller(timeout_ms=timeout_ms)
        ev = threading.Event()
        ch.call_method(
            HANDSHAKE_SERVICE,
            COLLECTIVE_METHOD,
            proposal(idx),
            cntl=cntl,
            done=lambda c, _ev=ev: _ev.set(),
        )
        pending.append((cntl, ev))
    # Short rejection watch before committing to our own session: the
    # accept phase reserves nothing, so a run proposal can still bounce
    # instantly (admission ELIMIT from an overlapping session, a server
    # mid-stop). A completed failure here means a party that will never
    # join — surface it now rather than waiting out the collective
    # backend's timeout. Bounded at _REJECT_WATCH_S (one local RPC round
    # trip), not the old always-burned 0.5 s.
    watch_deadline = time.monotonic() + _REJECT_WATCH_S
    while time.monotonic() < watch_deadline:
        for cntl, ev in pending:
            if ev.is_set() and cntl.failed():
                raise RuntimeError(
                    f"collective proposal rejected: {cntl.error_text}"
                )
        time.sleep(0.005)
    span = _start_session_span(party_ids, client_index, steps, width)
    own, elapsed = _run_observed_session(
        span, party_ids, client_index, steps, width, seed
    )
    _end_session_span(span)
    checksums = []
    deadline = time.monotonic() + timeout_ms / 1000.0  # shared, not per-peer
    for cntl, ev in pending:
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            raise TimeoutError("collective peer never completed")
        if cntl.failed():
            raise RuntimeError(f"collective peer failed: {cntl.error_text}")
        checksums.append(
            json.loads(cntl.response_payload.decode())["checksum"]
        )
    return {"own": own, "elapsed_s": elapsed, "server_checksums": checksums}
