"""Quantized collectives — block-wise int8/int4 allreduce on the method
plane (EQuARX, PAPERS.md 2506.17615): ~4x fewer bytes on the wire.

Every collective session so far shipped full-width float32 rows across
the party axis: a width-W pmean moves W bytes per party per step.  On a
bandwidth-bound mesh that is the whole cost, and EQuARX's observation is
that gradients and activations tolerate block-wise quantization: split
the row into blocks of B float32s, keep one scale per block, ship int8
(or int4) values + scales, dequantize and reduce on arrival.  The wire
footprint drops to ``nfloats + nblocks`` bytes (int8) or
``nfloats/2 + nblocks`` (int4 packs two values per byte) — ~0.26x /
~0.13x of the exact row.

Design decisions, in the order they matter:

- **Scales are powers of two** (one int8 EXPONENT per block, not a
  float32 scale).  Three wins: (1) the scale itself costs 1 byte, not 4;
  (2) quantize and dequantize are EXACT arithmetic — multiplying by 2^e
  only moves the float exponent, so ``dequantize(quantize(v))`` round-
  trips to precisely the value the wire carried on every party, with no
  FP-order luck; (3) the round trip is IDEMPOTENT
  (``quantize(dequantize(q, e))`` dequantizes back to the identical
  bytes), which is what lets quantized CHECKPOINT rings resume
  byte-identically (parallel/mc_dispatch.py): the first replayed step
  re-quantizes the restored state to exactly what the undisturbed chain
  quantized.  The cost vs an optimal float scale is at most one extra
  bit of quantization error — bounded below.
- **Deterministic rounding** (round-half-to-even), never stochastic:
  every party must compute the identical program or the lockstep chain
  diverges — the collective plane's fingerprint contract extends into
  the arithmetic.
- **Block-aligned chunking**: the kernels are ``chunkable=True`` (an
  overlap session may split the row into sub-collectives) but a chunk
  boundary must fall on a block boundary, or the chunk would recompute
  scales from partial blocks and diverge from the full-width bytes.
  ``DeviceMethod.chunk_align = 4 * block`` enforces it at admission,
  pre-lockstep, like every other chunk-safety rule.

Error bound (documented in docs/DEVICE_PLANE.md and gated in
dryrun_multichip): per element, one quantized pmean step differs from
the exact mean by at most ``max_p amax_block(p) / qmax`` — each party's
per-block error is ≤ scale/2, and the power-of-two scale is < 2x the
optimal ``amax/qmax``.  int8 (qmax 127): ≤ ~0.8% of the block's peak
magnitude; int4 (qmax 7): ≤ ~14%.  A K-step chain compounds at most
K times the single-step bound (conservative: post-mean magnitudes only
shrink).  NaN/Inf rows are the caller's bug — the kernels assume finite
float32 data, exactly like the exact pmean.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

QUANT_MODES = ("none", "int8", "int4")
DEFAULT_BLOCK = 32  # float32 values per scale block
_QMAX = {"int8": 127, "int4": 7}

# exponent clamp: int8 storage and exp2() exactness both hold inside
# the normal-float32 exponent range; a block whose amax sits outside it
# quantizes to zeros (subnormal data) or saturates (near-f32-max data)
_E_MIN, _E_MAX = -126, 127


def qmax_for(mode: str) -> int:
    return _QMAX[mode]


def supports(width: int, mode: str, block: int = DEFAULT_BLOCK) -> bool:
    """Whether a width-``width``-byte row quantizes in ``mode``: float32
    rows only, whole blocks only (a trailing partial block would need its
    own scale arithmetic and break chunk alignment), and int4 packs two
    values per byte so blocks must hold an even count."""
    if mode not in _QMAX or block <= 0:
        return False
    if width % 4 != 0:
        return False
    nfloats = width // 4
    if nfloats % block != 0:
        return False
    if mode == "int4" and block % 2 != 0:
        return False
    return True


def wire_bytes(width: int, mode: str, block: int = DEFAULT_BLOCK) -> int:
    """Bytes one party ships per step for a width-byte row: the quantized
    values plus one int8 exponent per block (the exact path ships
    ``width``).  Derived from the storage dtypes, not hand math."""
    if mode == "none":
        return int(width)
    if not supports(width, mode, block):
        raise ValueError(f"width {width} does not quantize as {mode}/{block}")
    nfloats = width // 4
    nblocks = nfloats // block
    vals = nfloats * np.dtype(np.int8).itemsize
    if mode == "int4":
        vals //= 2  # two 4-bit values packed per byte
    return vals + nblocks * np.dtype(np.int8).itemsize


# -- the numpy twin ------------------------------------------------------------
#
# Host-side mirror of the jax arithmetic below, used by checkpoint
# restore/reshard (parallel/mc_dispatch._restore_state dequantizes ring
# shards on the host) and by tests as the oracle.  Every operation is
# exact (comparisons, frexp, power-of-two scaling), so the two twins
# agree BITWISE — the property the restore path depends on.


def np_block_exponents(xf: np.ndarray, mode: str, block: int) -> np.ndarray:
    """Per-block power-of-two scale exponents: the smallest e with
    ``amax / 2^e <= qmax``.  frexp gives amax/qmax = m * 2^ex with
    m in [0.5, 1); ceil(log2) is ex except exactly at m == 0.5."""
    qmax = _QMAX[mode]
    xb = np.abs(xf.reshape(-1, block)).max(axis=1) / np.float32(qmax)
    m, ex = np.frexp(xb.astype(np.float32))
    e = ex - (m == np.float32(0.5))
    return np.clip(e, _E_MIN, _E_MAX).astype(np.int8)


def np_quantize(
    xf: np.ndarray, mode: str, block: int = DEFAULT_BLOCK
) -> Tuple[np.ndarray, np.ndarray]:
    """float32[nfloats] -> (wire values, int8 exponents).  int8 mode
    returns int8[nfloats]; int4 packs value pairs into uint8[nfloats/2]
    (low nibble first, offset-8 so [-7, 7] maps to [1, 15])."""
    xf = np.asarray(xf, dtype=np.float32).reshape(-1)
    qmax = _QMAX[mode]
    e = np_block_exponents(xf, mode, block)
    scale = np.exp2(e.astype(np.float32))
    q = np.clip(
        np.round(xf.reshape(-1, block) / scale[:, None]), -qmax, qmax
    ).astype(np.int8)
    q = q.reshape(-1)
    if mode == "int4":
        u = (q.astype(np.int16) + 8).astype(np.uint8)
        q = (u[0::2] | (u[1::2] << 4)).astype(np.uint8)
    return q, e


def np_dequantize(
    q: np.ndarray, e: np.ndarray, mode: str, block: int = DEFAULT_BLOCK
) -> np.ndarray:
    """Inverse of :func:`np_quantize` — exact (power-of-two scaling)."""
    if mode == "int4":
        u = np.asarray(q, dtype=np.uint8)
        lo = (u & 0xF).astype(np.int16) - 8
        hi = (u >> 4).astype(np.int16) - 8
        q = np.stack([lo, hi], axis=1).reshape(-1).astype(np.int8)
    q = np.asarray(q, dtype=np.int8)
    scale = np.exp2(np.asarray(e, dtype=np.int8).astype(np.float32))
    return (
        q.reshape(-1, block).astype(np.float32) * scale[:, None]
    ).reshape(-1)


def np_quantized_pmean(
    rows: List[np.ndarray], steps: int, mode: str, block: int = DEFAULT_BLOCK
) -> np.ndarray:
    """Host model of the K-step quantized pmean chain: each step every
    party quantizes its row, the dequantized contributions average, and
    every party continues from the mean.  Float summation order may
    differ from XLA's by an ulp — compare with a tolerance, not bytes
    (the byte-exactness claims live in the round-trip, not the sum)."""
    cur = [np.asarray(r, dtype=np.float32).reshape(-1) for r in rows]
    for _ in range(int(steps)):
        deq = [np_dequantize(*np_quantize(r, mode, block), mode, block)
               for r in cur]
        m = (np.sum(np.stack(deq), axis=0, dtype=np.float32)
             / np.float32(len(cur)))
        cur = [m.copy() for _ in cur]
    return cur[0]


def pmean_error_bound(
    rows: List[np.ndarray], steps: int, mode: str, block: int = DEFAULT_BLOCK
) -> float:
    """The documented worst-case |quantized - exact| for a K-step pmean
    chain of these operands: per step each party contributes ≤ scale/2 ≤
    amax_block/qmax of error to the mean, so one step is bounded by the
    max over parties of the per-block amax / qmax, and K steps compound
    ≤ K times that (magnitudes only shrink under pmean)."""
    qmax = _QMAX[mode]
    worst = 0.0
    for r in rows:
        xb = np.abs(np.asarray(r, dtype=np.float32).reshape(-1, block))
        worst = max(worst, float(xb.max()))
    return steps * worst / qmax


# -- the jax kernels -----------------------------------------------------------


def _jq_quantize(xf, mode: str, block: int):
    """jax twin of np_quantize over a [rows, nfloats] float32 array:
    returns (wire values [rows, ...], exponents int8 [rows, nblocks])."""
    import jax.numpy as jnp

    qmax = _QMAX[mode]
    rows = xf.shape[0]
    xb = xf.reshape(rows, -1, block)
    amax = jnp.max(jnp.abs(xb), axis=-1) / jnp.float32(qmax)
    m, ex = jnp.frexp(amax)
    e = jnp.clip(
        ex - (m == jnp.float32(0.5)).astype(ex.dtype), _E_MIN, _E_MAX
    ).astype(jnp.int8)
    scale = jnp.exp2(e.astype(jnp.float32))
    q = jnp.clip(
        jnp.round(xb / scale[..., None]), -qmax, qmax
    ).astype(jnp.int8).reshape(rows, -1)
    if mode == "int4":
        u = (q.astype(jnp.int16) + 8).astype(jnp.uint8)
        q = (u[:, 0::2] | (u[:, 1::2] << 4)).astype(jnp.uint8)
    return q, e


def _jq_dequantize(q, e, mode: str, block: int):
    """jax twin of np_dequantize over [rows, ...] wire arrays."""
    import jax.numpy as jnp

    rows = q.shape[0]
    if mode == "int4":
        lo = (q & 0xF).astype(jnp.int16) - 8
        hi = (q >> 4).astype(jnp.int16) - 8
        q = jnp.stack([lo, hi], axis=-1).reshape(rows, -1).astype(jnp.int8)
    scale = jnp.exp2(e.astype(jnp.float32))
    return (
        q.reshape(rows, -1, block).astype(jnp.float32) * scale[..., None]
    ).reshape(rows, -1)


def _make_quantized_pmean_kernel(mode: str, block: int):
    """Mint the quantized pmean kernel for one (mode, block): quantize
    the own row, all_gather the QUANTIZED representation over the party
    axis (this is where the wire bytes shrink — the gathered arrays are
    the int8/int4 values + int8 exponents, never the float32 row),
    dequantize every party's contribution and average.  The closure
    cells (mode, block) enter the DeviceMethod fingerprint, so two
    parametrizations can never silently alias."""

    def kernel(data, n, _mode=mode, _block=block):
        import jax
        import jax.numpy as jnp

        f = jax.lax.bitcast_convert_type(
            data.reshape(-1, 4), jnp.float32
        )[None, :]
        q, e = _jq_quantize(f, _mode, _block)
        # the wire crossing: per party, len(q[0]) + len(e[0]) bytes
        # instead of the width-byte float row
        gq = jax.lax.all_gather(q[0], "par")
        ge = jax.lax.all_gather(e[0], "par")
        v = _jq_dequantize(gq, ge, _mode, _block)
        nparties = jax.lax.psum(1, "par")
        m = jnp.sum(v, axis=0) / jnp.float32(nparties)
        return jax.lax.bitcast_convert_type(m, jnp.uint8).reshape(-1), n

    return kernel


_variant_cache: Dict[tuple, "object"] = {}
_variant_lock = threading.Lock()


def quantized_pmean_dm(
    width: int, mode: str, block: int = DEFAULT_BLOCK
):
    """The quantized pmean DeviceMethod for one (width, mode, block) —
    cached so every resolution in this process hands back the same
    object (and therefore the same fingerprint the peers computed from
    the identical factory)."""
    from incubator_brpc_tpu.rpc.device_method import DeviceMethod

    if not supports(width, mode, block):
        return None
    key = (int(width), mode, int(block))
    with _variant_lock:
        dm = _variant_cache.get(key)
        if dm is None:
            dm = DeviceMethod(
                _make_quantized_pmean_kernel(mode, block),
                width=width,
                chunkable=True,
            )
            dm.quant_mode = mode
            dm.quant_block = int(block)
            dm.chunk_align = 4 * int(block)
            dm.collective_bytes = wire_bytes(width, mode, block)
            _variant_cache[key] = dm
        return dm


def attach_pmean_variants(dm, width: int, block: int = DEFAULT_BLOCK):
    """Hang the int8/int4 pmean variants off an exact pmean DeviceMethod
    (parallel/mc_collective mints one per width): the session plane's
    ``quantize=`` knob resolves through ``DeviceMethod.quantized``, and a
    width that doesn't block-align simply gets no variant — the knob
    then rejects cleanly pre-lockstep."""
    for mode in ("int8", "int4"):
        if supports(width, mode, block):
            var = quantized_pmean_dm(width, mode, block)
            if var is not None:
                dm.quant_variants[mode] = var
    return dm
