"""parallel — mesh + collective lowerings of the reference's combo channels.

SURVEY.md §2.5 maps each reference distribution primitive to its TPU-native
equivalent; this package implements that column:

| reference primitive            | here                                   |
|--------------------------------|----------------------------------------|
| ParallelChannel fan-out/merge  | ``fanout``/``merge`` (all_gather/psum) |
| PartitionChannel sharding      | ``partition_exchange`` (all_to_all)    |
| Streaming RPC credit window    | ``ring_stream`` (ppermute ring)        |
| SelectiveChannel replica sets  | replica groups over mesh sub-axes      |
"""

from incubator_brpc_tpu.parallel.mesh import (
    FABRIC_AXES,
    default_axis_sizes,
    make_fabric_mesh,
)
from incubator_brpc_tpu.parallel.collective import (
    fanout,
    merge,
    partition_exchange,
    ring_stream,
    ring_allgather,
)

__all__ = [
    "FABRIC_AXES",
    "default_axis_sizes",
    "make_fabric_mesh",
    "fanout",
    "merge",
    "partition_exchange",
    "ring_stream",
    "ring_allgather",
]
