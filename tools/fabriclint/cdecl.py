"""Minimal C declaration parser for the tb_* C-ABI headers.

fabriclint's FFI checker needs the *shape* of every ``extern "C"``
surface in src/tbutil/tbutil.h and src/tbnet/tbnet.h: function
declarations (return type, argument types), function-pointer typedefs
(callback layouts), and struct layouts (field offsets/widths under
natural alignment).  The headers are deliberately plain C89-style
declarations — no macros in signatures, no nested parens except in
function-pointer typedefs — so a tokenizing parser a few hundred lines
long covers them completely, and anything it cannot parse is reported
as a violation rather than skipped (an unparsed declaration is an
unchecked declaration).

This is NOT a general C parser.  It exists so the hand-maintained
ctypes table in incubator_brpc_tpu/native.py can be diffed against the
compiler-enforced truth on every test run.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# canonical type model
# ---------------------------------------------------------------------------

# scalar name -> (bits, signed).  LP64 (the only ABI the native plane
# builds for; the Makefile targets linux-gnu).
SCALARS: Dict[str, Tuple[int, bool]] = {
    "char": (8, True),
    "int8_t": (8, True),
    "uint8_t": (8, False),
    "int16_t": (16, True),
    "uint16_t": (16, False),
    "int": (32, True),
    "unsigned": (32, False),
    "int32_t": (32, True),
    "uint32_t": (32, False),
    "long": (64, True),
    "int64_t": (64, True),
    "uint64_t": (64, False),
    "size_t": (64, False),
    "ssize_t": (64, True),
    # deliberately NO floating-point entries: float/double pass in xmm
    # registers on SysV AMD64, so modeling them as 64-bit integers would
    # bless an ABI-broken integer binding.  The current ABI has no float
    # parameters; if one is ever added, its declaration lands in
    # `unparsed` (an ffi-parse violation) until float support is added
    # here AND in ffi_check's ctypes mapping, both deliberately.
}


@dataclass(frozen=True)
class CType:
    """Canonical C type: a scalar, void, or a pointer.

    kind: "void" | "scalar" | "ptr"
    For scalars, ``bits``/``signed_`` describe the width.  For
    pointers, ``pointee`` names what is pointed at:
      "void", "char", "scalar:<name>", "struct:<name>",
      "opaque:<name>", "fn:<typedef name>".
    """

    kind: str
    bits: int = 0
    signed_: bool = True
    pointee: str = ""

    def __str__(self) -> str:  # diagnostics only
        if self.kind == "ptr":
            return f"{self.pointee}*"
        if self.kind == "scalar":
            return f"{'i' if self.signed_ else 'u'}{self.bits}"
        return self.kind


@dataclass
class CFunc:
    name: str
    ret: CType
    args: List[CType]
    line: int  # 1-based line in the header (diagnostics)


@dataclass
class CFuncPtr:
    name: str
    ret: CType
    args: List[CType]
    line: int


@dataclass
class CStructField:
    name: str
    bits: int
    signed_: bool
    offset_bits: int
    is_ptr: bool = False


@dataclass
class CStruct:
    name: str
    fields: List[CStructField]
    size_bits: int
    line: int


@dataclass
class Header:
    path: str
    funcs: Dict[str, CFunc] = field(default_factory=dict)
    funcptrs: Dict[str, CFuncPtr] = field(default_factory=dict)
    structs: Dict[str, CStruct] = field(default_factory=dict)
    opaques: List[str] = field(default_factory=list)
    unparsed: List[Tuple[int, str]] = field(default_factory=list)


# ---------------------------------------------------------------------------
# lexing helpers
# ---------------------------------------------------------------------------


def _strip_comments(text: str) -> str:
    """Blank out comments, preserving newlines so line numbers survive."""

    out = []
    i, n = 0, len(text)
    while i < n:
        if text.startswith("//", i):
            j = text.find("\n", i)
            j = n if j < 0 else j
            i = j
        elif text.startswith("/*", i):
            j = text.find("*/", i)
            j = n - 2 if j < 0 else j
            out.append("".join(c if c == "\n" else " " for c in text[i : j + 2]))
            i = j + 2
        else:
            out.append(text[i])
            i += 1
    return "".join(out)


def _strip_cpp(text: str) -> str:
    """Blank preprocessor lines and the extern "C" scaffolding.

    Both the opening ``extern "C" {`` and its lone closing ``}`` are
    blanked so the chunk splitter's brace-depth tracking only ever sees
    struct braces — otherwise the closer would drive depth negative and
    any declaration after the block would be mis-split.
    """

    lines = []
    for ln in text.split("\n"):
        s = ln.strip()
        if s.startswith("#") or s == "}":
            lines.append("")
        elif s.startswith('extern "C"'):
            rest = s[len('extern "C"') :].strip()
            if rest in ("", "{"):
                lines.append("")  # the block form: scaffolding only
            else:
                # one-line form (`extern "C" int f(...);`): keep the
                # declaration so it is parsed/reported, not vanished
                lines.append(ln.replace('extern "C"', "          ", 1))
        else:
            lines.append(ln)
    return "\n".join(lines)


def parse_type(spec: str, header: "Header") -> Optional[CType]:
    """Canonicalize one C type spec (parameter name already removed)."""

    toks = spec.replace("*", " * ").split()
    toks = [t for t in toks if t not in ("const", "volatile", "struct")]
    stars = toks.count("*")
    base = [t for t in toks if t != "*"]
    if not base:
        return None
    if len(base) == 2 and base == ["unsigned", "int"]:
        base = ["unsigned"]
    if len(base) != 1:
        return None
    name = base[0]
    if stars == 0:
        if name == "void":
            return CType("void")
        if name in SCALARS:
            bits, sg = SCALARS[name]
            return CType("scalar", bits, sg)
        if name in header.funcptrs:  # callback passed by typedef value
            return CType("ptr", pointee=f"fn:{name}")
        return None
    if stars == 1:
        if name == "void":
            return CType("ptr", pointee="void")
        if name == "char":
            return CType("ptr", pointee="char")
        if name in header.structs:
            return CType("ptr", pointee=f"struct:{name}")
        if name in header.opaques:
            return CType("ptr", pointee=f"opaque:{name}")
        if name in SCALARS:
            return CType("ptr", pointee=f"scalar:{name}")
        return None
    return None  # ** never appears on this ABI except in fn-ptr typedef args


_SPLIT_ARGS = re.compile(r",")


def _parse_arglist(arglist: str, header: Header) -> Optional[List[CType]]:
    arglist = arglist.strip()
    if arglist in ("", "void"):
        return []
    out: List[CType] = []
    for raw in _SPLIT_ARGS.split(arglist):
        raw = raw.strip()
        if not raw:
            return None
        # drop the parameter name: the last identifier, unless the spec is
        # a bare type ("tb_iobuf* body" -> drop "body"; "size_t" -> keep).
        m = re.match(r"^(.*?)([A-Za-z_][A-Za-z0-9_]*)$", raw)
        spec = raw
        if m:
            head = m.group(1).strip()
            # "char** resp": head "char**" is a full type; "uint64_t" with
            # empty head is the type itself, keep it.
            if head:
                spec = head
        t = parse_type(spec, header)
        if t is None and m and m.group(1).strip() == "":
            t = parse_type(raw, header)  # unnamed parameter
        if t is None and spec.replace(" ", "").endswith("**"):
            # pointer-to-pointer out-param (tb_native_fn's char** resp):
            # canonically just "a pointer slot the callee fills"
            t = CType("ptr", pointee="ptr")
        if t is None:
            return None
        out.append(t)
    return out


_FUNCPTR_RE = re.compile(
    r"^typedef\s+(?P<ret>[A-Za-z_][A-Za-z0-9_ ]*?\**)\s*"
    r"\(\s*\*\s*(?P<name>[A-Za-z_][A-Za-z0-9_]*)\s*\)\s*"
    r"\((?P<args>.*)\)$",
    re.S,
)
_OPAQUE_RE = re.compile(
    r"^typedef\s+struct\s+(?P<tag>[A-Za-z_][A-Za-z0-9_]*)\s+(?P<name>[A-Za-z_][A-Za-z0-9_]*)$"
)
_STRUCT_RE = re.compile(
    r"^typedef\s+struct(?:\s+[A-Za-z_][A-Za-z0-9_]*)?\s*\{(?P<body>.*)\}\s*"
    r"(?P<name>[A-Za-z_][A-Za-z0-9_]*)$",
    re.S,
)
_FUNC_RE = re.compile(
    r"^(?P<ret>[A-Za-z_][A-Za-z0-9_ ]*?\**)\s*"
    r"(?P<name>[A-Za-z_][A-Za-z0-9_]*)\s*\((?P<args>.*)\)$",
    re.S,
)


def _parse_struct_body(
    body: str, header: Header
) -> Optional[Tuple[List[CStructField], int]]:
    fields: List[CStructField] = []
    offset = 0
    for decl in body.split(";"):
        decl = decl.strip()
        if not decl:
            continue
        m = re.match(r"^(.*?)([A-Za-z_][A-Za-z0-9_]*)$", decl)
        if not m:
            return None
        spec, fname = m.group(1).strip(), m.group(2)
        t = parse_type(spec, header)
        if t is None:
            return None
        if t.kind == "ptr":
            bits, sg, is_ptr = 64, False, True
        elif t.kind == "scalar":
            bits, sg, is_ptr = t.bits, t.signed_, False
        else:
            return None
        offset = (offset + bits - 1) // bits * bits  # natural alignment
        fields.append(CStructField(fname, bits, sg, offset, is_ptr))
        offset += bits
    if not fields:
        return None
    align = max(f.bits for f in fields)
    size = (offset + align - 1) // align * align
    return fields, size


def parse_header(
    path: str, text: Optional[str] = None, base: Optional[Header] = None
) -> Header:
    """Parse one header into the canonical declaration model.

    ``base`` seeds the type namespace with another header's typedefs —
    tbnet.h uses tbutil.h's ``tb_iobuf``/``tb_release_fn`` in its own
    signatures, so it must be parsed with tbutil.h as base.
    """

    if text is None:
        with open(path, "r") as fh:
            text = fh.read()
    header = Header(path=path)
    if base is not None:
        header.structs.update(base.structs)
        header.funcptrs.update(base.funcptrs)
        header.opaques.extend(base.opaques)
    clean = _strip_cpp(_strip_comments(text))
    # split into ';'-terminated declarations, tracking brace depth so
    # struct bodies stay one chunk
    chunks: List[Tuple[int, str]] = []
    buf: List[str] = []
    depth = 0
    line = 1
    start_line = 1
    for ch in clean:
        if not buf and ch not in " \n\t":
            start_line = line
        if ch == "\n":
            line += 1
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        if ch == ";" and depth == 0:
            chunk = "".join(buf).strip()
            if chunk:
                chunks.append((start_line, chunk))
            buf = []
        else:
            buf.append(ch)
    for start, chunk in chunks:
        norm = " ".join(chunk.split())
        m = _OPAQUE_RE.match(norm)
        if m:
            header.opaques.append(m.group("name"))
            continue
        m = _STRUCT_RE.match(norm)
        if m:
            parsed = _parse_struct_body(m.group("body"), header)
            if parsed is None:
                header.unparsed.append((start, norm))
                continue
            fields, size = parsed
            header.structs[m.group("name")] = CStruct(
                m.group("name"), fields, size, start
            )
            continue
        m = _FUNCPTR_RE.match(norm)
        if m:
            ret = parse_type(m.group("ret"), header)
            args = _parse_arglist(m.group("args"), header)
            if ret is None or args is None:
                header.unparsed.append((start, norm))
                continue
            header.funcptrs[m.group("name")] = CFuncPtr(
                m.group("name"), ret, args, start
            )
            continue
        m = _FUNC_RE.match(norm)
        if m and "typedef" not in norm:
            ret = parse_type(m.group("ret"), header)
            args = _parse_arglist(m.group("args"), header)
            if ret is None or args is None:
                header.unparsed.append((start, norm))
                continue
            header.funcs[m.group("name")] = CFunc(
                m.group("name"), ret, args, start
            )
            continue
        header.unparsed.append((start, norm))
    return header


def merge_headers(headers: List[Header]) -> Header:
    """Fold several headers into one namespace (tbnet includes tbutil)."""

    merged = Header(path="+".join(h.path for h in headers))
    for h in headers:
        merged.funcs.update(h.funcs)
        merged.funcptrs.update(h.funcptrs)
        merged.structs.update(h.structs)
        merged.opaques.extend(h.opaques)
        merged.unparsed.extend(h.unparsed)
    return merged
