"""fabriclint — in-repo static analysis for the FFI boundary and hot path.

PRs 2, 4, and 5 moved the request hot path into concurrent C++
(src/tbnet) reached from Python through a hand-maintained ctypes table —
the exact seam where drift corrupts silently instead of failing loudly.
The reference codebase leans on compiler-enforced headers plus
gtest/sanitizer CI for this; fabriclint is that role for a
Python-driven native plane:

- **ffi** (ffi_check.py): the ``extern "C"`` declarations in
  src/tbutil/tbutil.h + src/tbnet/tbnet.h, parsed, cross-checked
  against ``native.SIGNATURES`` — names, arity, integer width and
  signedness, callback (CFUNCTYPE) layouts, and struct layouts
  (ctypes mirror AND the numpy drain dtype).
- **hotpath** (hotpath.py): functions marked ``# fabriclint: hotpath``
  must not acquire locks, log, do I/O, or run per-record Python loops
  (the vectorization regression class PR 4 fought).
- **registry** (registry_lint.py): every ``define_flag`` is read
  somewhere and carries help text; exposed bvar names are valid
  Prometheus identifiers and the ``native_*``/``mc_*`` families match
  docs/OBSERVABILITY.md.
- **lifetime** (lifetime.py): every C callback registered from Python
  is held in a keepalive before crossing the FFI (the classic ctypes
  GC-of-live-callback crash), checked structurally.
- **errcheck** (errcheck.py): no ``LIB.tb_*`` call with an
  error-indicating return is silently discarded.

Run everything: ``python -m tools.fabriclint`` (or ``make lint``); the
same checks run inside tier-1 via tests/test_static_analysis.py.
Sanitizer wiring lives in san.py (``make san``).

Exemptions are inline and reasoned::

    # fabriclint: allow(<rule>) <non-empty reason>

on the violating line or the line above it.  An empty reason is itself
a violation (``bad-allow``) — the annotation documents *why* the rule
does not apply, not merely that someone silenced it.
"""

from __future__ import annotations

import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Rules owned by the sibling concurrency checker (tools/fabricverify).
# They are registered here because the two tools share ONE annotation
# grammar: a single ``# fabriclint: allow(<rule>) <reason>`` scanner must
# recognize every rule either tool can fire, or a legitimate fabricverify
# exemption would be reported as bad-allow by fabriclint and vice versa.
VERIFY_RULES = (
    "lock-cycle",       # cycle in the global lock-ordering graph
    "lock-unmodeled",   # a lock primitive the analyzer could not bind
    "lifecycle-borrow",     # SimpleDataPool.borrow with no give_back path
    "lifecycle-timer",      # TimerThread.schedule with no unschedule path
    "lifecycle-callback",   # hook registration with no teardown removal
    "model-stuck",          # reachable model state with no enabled action
    "model-unsafe",         # reachable state violating a safety property
    "model-unrevivable",    # state from which recovery is unreachable
)

# Rules owned by the C++-plane analyzer (tools/fabricscan) — registered
# here for the same reason as VERIFY_RULES: one annotation grammar, one
# scanner validating every allow() either tool can exempt.
SCAN_RULES = (
    "wire-bounds",      # tainted wire length reaches a sink unguarded
    "ownership",        # owned field touched from the wrong thread role
    "owner-missing",    # mutable shared C++ state with no declared owner
    "plane-parity",     # a mirrored constant drifted between the planes
    "scan-parse",       # C++ the model/extractors could not cover
)

RULES = (
    "ffi-missing",      # sigs entry with no header declaration
    "ffi-unbound",      # header function with no sigs entry
    "ffi-arity",        # argument count mismatch
    "ffi-type",         # width/signedness/kind mismatch
    "ffi-callback",     # CFUNCTYPE layout mismatch vs header typedef
    "ffi-struct",       # struct layout mismatch (ctypes or numpy dtype)
    "ffi-parse",        # declaration the header parser could not model
    "hotpath-lock",
    "hotpath-log",
    "hotpath-io",
    "hotpath-loop",
    "flag-dead",
    "flag-undocumented",
    "bvar-name",
    "bvar-undocumented",
    "ffi-keepalive",
    "ffi-unchecked",
    "bad-allow",
) + VERIFY_RULES + SCAN_RULES


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        rel = os.path.relpath(self.path, REPO_ROOT)
        return f"{rel}:{self.line}: [{self.rule}] {self.message}"


def to_records(violations: Iterable["Violation"]) -> List[Dict[str, object]]:
    """Violations as ``{rule, file, line, reason}`` records — the
    machine-readable report schema shared by ``--json`` on fabriclint and
    fabricverify, stable so CI tooling can diff violation sets across
    commits (files repo-relative, one record per violation)."""

    return [
        {
            "rule": v.rule,
            "file": os.path.relpath(v.path, REPO_ROOT),
            "line": v.line,
            "reason": v.message,
        }
        for v in violations
    ]


_ALLOW_RE = re.compile(
    r"(?:#|//)\s*fabriclint:\s*allow\(([a-z0-9-]+)\)\s*(.*)$"
)
_HOTPATH_RE = re.compile(r"#\s*fabriclint:\s*hotpath\b")


@dataclass
class Annotations:
    """Per-file fabriclint comment annotations."""

    # line -> list of (rule, reason)
    allows: Dict[int, List[Tuple[str, str]]] = field(default_factory=dict)
    hotpath_lines: List[int] = field(default_factory=list)
    bad: List[Violation] = field(default_factory=list)  # malformed allows


def scan_annotations(path: str, source: Optional[str] = None) -> Annotations:
    """Collect ``# fabriclint:`` comments with their line numbers.

    Works for Python (via tokenize, so strings containing the marker
    text don't count) and for C/C++ headers (line-regex fallback).
    """

    if source is None:
        with open(path, "r") as fh:
            source = fh.read()
    ann = Annotations()

    def _record(line_no: int, text: str) -> None:
        m = _ALLOW_RE.search(text)
        if m:
            rule, reason = m.group(1), m.group(2).strip()
            if rule not in RULES:
                ann.bad.append(
                    Violation(
                        "bad-allow", path, line_no,
                        f"allow() names unknown rule {rule!r}",
                    )
                )
            elif not reason:
                ann.bad.append(
                    Violation(
                        "bad-allow", path, line_no,
                        f"allow({rule}) has no reason — exemptions must "
                        "say why the rule does not apply",
                    )
                )
            else:
                ann.allows.setdefault(line_no, []).append((rule, reason))
            return
        if _HOTPATH_RE.search(text):
            ann.hotpath_lines.append(line_no)

    if path.endswith((".h", ".hh", ".hpp", ".c", ".cc", ".cpp")):
        for i, ln in enumerate(source.split("\n"), 1):
            if "fabriclint:" in ln:
                _record(i, ln)
        return ann
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT and "fabriclint:" in tok.string:
                _record(tok.start[0], tok.string)
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass
    return ann


def allowed(ann: Annotations, rule: str, line: int) -> bool:
    """An allow applies to its own line and the line directly below it
    (i.e. written inline or on the line above the violating statement)."""

    for ln in (line, line - 1):
        for r, _reason in ann.allows.get(ln, ()):  # reason checked at scan
            if r == rule:
                return True
    return False


def iter_py_files(
    roots: Iterable[str] = ("incubator_brpc_tpu", "tools", "examples"),
    include_tests: bool = False,
) -> List[str]:
    """Product-code Python files in lint scope, repo-relative roots."""

    out: List[str] = []
    roots = list(roots) + (["tests"] if include_tests else [])
    for root in roots:
        top = os.path.join(REPO_ROOT, root)
        if os.path.isfile(top) and top.endswith(".py"):
            out.append(top)
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", "build")
            ]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


def run_all() -> List[Violation]:
    """Run every checker over the repo; returns unexempted violations."""

    from tools.fabriclint import (
        errcheck,
        ffi_check,
        hotpath,
        lifetime,
        registry_lint,
    )

    out: List[Violation] = []
    out.extend(ffi_check.check())
    out.extend(hotpath.check())
    out.extend(registry_lint.check())
    out.extend(lifetime.check())
    out.extend(errcheck.check())
    # several passes scan the same files for annotations and each reports
    # malformed allows it sees — dedupe on identity
    seen = set()
    unique: List[Violation] = []
    for v in out:
        key = (v.rule, v.path, v.line, v.message)
        if key not in seen:
            seen.add(key)
            unique.append(v)
    return unique
