"""Sanitizer harness for the native plane (`make san`).

The reference hardens its C++ core with gtest suites run under
ASAN/TSAN in CI; this repo's native plane is driven from Python, so the
harness builds sanitizer variants of libtbutil.so (src/Makefile `asan`/
`tsan` targets), points the ctypes loader at them via ``TBNET_LIB``,
preloads the matching runtime into the interpreter, and runs:

- **ASAN+UBSAN**: the native test subset (tests/test_native_plane.py,
  tests/test_native_baidu.py) — heap errors, UB (UBSAN findings are
  fatal via -fno-sanitize-recover).
- **TSAN**: the telemetry-ring multi-producer stress
  (TestTelemetryRingStress) at a reduced burn — the lock-free
  structures' race coverage — plus the scheduler contention stress
  (TestSchedulerContentionStress: worker_pool + timer_thread
  schedule/unschedule storm against stop) under the same sanitized
  interpreter.  Only the instrumented C++ is tracked; the
  uninstrumented interpreter is invisible to TSAN, so reports are
  tbnet/tbutil races, not Python noise.

Support is probed, not assumed: no g++, no sanitizer runtime, or a
runtime that cannot be preloaded into Python → the run SKIPS cleanly
(exit 0 with a [skip] line), matching the tier-1 tests' probe-gated
skip.  A failure in a supported environment exits nonzero.

Suppressions: tools/fabriclint/tsan.supp is committed and carries ONE
justified entry — the glibc ``_dl_deallocate_tls`` TLS-teardown class,
whose futex synchronization lives in uninstrumented libc and is
invisible to TSAN (full rationale in the file).  Every report in
instrumented code itself gets fixed, not suppressed.

Usage::

    python -m tools.fabriclint.san            # both sanitizers
    python -m tools.fabriclint.san --asan     # ASAN/UBSAN subset only
    python -m tools.fabriclint.san --tsan     # TSAN ring stress only
    python -m tools.fabriclint.san --probe    # report support and exit
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Optional, Tuple

from tools.fabriclint import REPO_ROOT

SRC_DIR = os.path.join(REPO_ROOT, "src")
ASAN_SO = os.path.join(SRC_DIR, "build", "libtbutil_asan.so")
TSAN_SO = os.path.join(SRC_DIR, "build", "libtbutil_tsan.so")
TSAN_SUPP = os.path.join(REPO_ROOT, "tools", "fabriclint", "tsan.supp")

ASAN_TESTS = [
    "tests/test_native_plane.py",
    "tests/test_native_baidu.py",
    # differential wire-decoder fuzz (ISSUE 12): random/mutated RpcMeta
    # blobs through the native scanner — exactly the hand-rolled parsing
    # ASAN exists to watch.  ISSUE 15 grew it with the traced-meta fuzz
    # (huge/zero/duplicate trace varints through the trace decode
    # branches and the traced pump template).
    "tests/test_wire_differential.py",
]
TSAN_TESTS = [
    # the lock-free telemetry ring under multi-producer fire (PR 6),
    # including the multi-reactor (4-ring) parametrization
    "tests/test_native_plane.py::TestTelemetryRingStress",
    # the Chase–Lev work-stealing deque: steal storm racing owner
    # push/pop + stop (the dispatch pool's queue, ISSUE 9)
    "tests/test_native_plane.py::TestWorkStealingDequeStress",
    # the scheduler plane: worker_pool + timer_thread schedule/unschedule
    # storm racing stop (the dynamic complement of fabricverify's static
    # lock-order pass)
    "tests/test_runtime_stress.py::TestSchedulerContentionStress",
]

_PROBE_SRC = 'extern "C" int fabriclint_probe(void) { return 7; }\n'


def _cxx() -> Optional[str]:
    return shutil.which(os.environ.get("CXX", "g++"))


def _runtime_of(cxx: str, lib: str) -> Optional[str]:
    """Resolve the preloadable sanitizer runtime (libasan.so.N...)."""

    try:
        out = subprocess.run(
            [cxx, f"-print-file-name={lib}"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return None
    if not out or out == lib:
        return None
    real = os.path.realpath(out)
    return real if os.path.exists(real) else None


def probe(kind: str) -> Tuple[bool, str]:
    """(supported, detail) — can this toolchain build a ``kind``-sanitized
    .so AND preload its runtime into a fresh interpreter?"""

    cxx = _cxx()
    if cxx is None:
        return False, "no C++ compiler on PATH"
    flag = {"asan": "address", "tsan": "thread"}[kind]
    rt = _runtime_of(cxx, {"asan": "libasan.so", "tsan": "libtsan.so"}[kind])
    if rt is None:
        return False, f"lib{flag[:1]}san runtime not found"
    with tempfile.TemporaryDirectory(prefix="fabriclint-san-") as td:
        src = os.path.join(td, "probe.cc")
        so = os.path.join(td, "probe.so")
        with open(src, "w") as fh:
            fh.write(_PROBE_SRC)
        try:
            rc = subprocess.run(
                [cxx, "-shared", "-fPIC", f"-fsanitize={flag}", "-o", so, src],
                capture_output=True, timeout=120,
            ).returncode
        except (OSError, subprocess.SubprocessError):
            return False, "sanitized compile failed"
        if rc != 0 or not os.path.exists(so):
            return False, "sanitized compile failed"
        env = dict(os.environ, LD_PRELOAD=rt)
        env.setdefault("ASAN_OPTIONS", "detect_leaks=0")
        try:
            r = subprocess.run(
                [
                    sys.executable, "-c",
                    "import ctypes, sys;"
                    f"l = ctypes.CDLL({so!r});"
                    "sys.exit(0 if l.fabriclint_probe() == 7 else 3)",
                ],
                capture_output=True, timeout=120, env=env,
            )
        except (OSError, subprocess.SubprocessError):
            return False, "python-under-sanitizer probe failed"
        if r.returncode != 0:
            return False, "sanitizer runtime cannot preload into python"
    return True, rt


def _build(target: str) -> bool:
    try:
        r = subprocess.run(
            ["make", "-C", SRC_DIR, target],
            capture_output=True, text=True, timeout=600,
        )
    except (OSError, subprocess.SubprocessError):
        return False
    if r.returncode != 0:
        sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
    return r.returncode == 0


def _pytest(args, env) -> Tuple[int, str]:
    cmd = [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider"]
    cmd += args
    full_env = dict(os.environ)
    full_env.update(env)
    full_env.setdefault("JAX_PLATFORMS", "cpu")
    timeout_s = int(os.environ.get("FABRICLINT_SAN_TIMEOUT", "1800"))
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, cwd=REPO_ROOT,
            env=full_env, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        # a hung sanitized run (e.g. a TSAN-visible deadlock — exactly
        # the bug class this harness hunts) is a FAILURE, not a crash
        out = (e.stdout or b"").decode("utf-8", "replace") if isinstance(
            e.stdout, bytes
        ) else (e.stdout or "")
        return 124, out + f"\n[san] run exceeded {timeout_s}s and was killed"
    return r.returncode, r.stdout + r.stderr


def _last_line(out: str) -> str:
    lines = [ln for ln in out.splitlines() if ln.strip()]
    return lines[-1].strip() if lines else "(no output)"


def _preflight_native(env) -> Optional[str]:
    """The sanitized .so must actually load — a silent pure-Python
    fallback would 'pass' the whole run without testing anything."""

    full_env = dict(os.environ)
    full_env.update(env)
    r = subprocess.run(
        [
            sys.executable, "-c",
            "from incubator_brpc_tpu import native; "
            "import sys; sys.exit(0 if native.NATIVE_AVAILABLE else 4)",
        ],
        capture_output=True, text=True, cwd=REPO_ROOT, env=full_env,
        timeout=300,
    )
    if r.returncode != 0:
        return f"sanitized library did not load: {r.stderr[-500:]}"
    return None


def run_asan() -> int:
    ok, detail = probe("asan")
    if not ok:
        print(f"[skip] asan: {detail}")
        return 0
    rt = detail
    if not _build("asan"):
        print("[FAIL] asan: build failed")
        return 1
    env = {
        "TBNET_LIB": ASAN_SO,
        "LD_PRELOAD": rt,
        "ASAN_OPTIONS": (
            "detect_leaks=0:abort_on_error=1:verify_asan_link_order=0"
        ),
        "UBSAN_OPTIONS": "print_stacktrace=1:halt_on_error=1",
    }
    err = _preflight_native(env)
    if err:
        print(f"[FAIL] asan: {err}")
        return 1
    rc, out = _pytest(ASAN_TESTS + ["-m", "not slow"], env)
    bad = (
        rc != 0
        or "ERROR: AddressSanitizer" in out
        or "runtime error:" in out
    )
    tail = "\n".join(out.splitlines()[-15:])
    if bad:
        print(f"[FAIL] asan/ubsan native subset:\n{tail}")
        return 1
    print(f"[ok] asan/ubsan native subset: {_last_line(out)}")
    return 0


def run_tsan() -> int:
    ok, detail = probe("tsan")
    if not ok:
        print(f"[skip] tsan: {detail}")
        return 0
    rt = detail
    if not _build("tsan"):
        print("[FAIL] tsan: build failed")
        return 1
    env = {
        "TBNET_LIB": TSAN_SO,
        "LD_PRELOAD": rt,
        # exitcode=66 turns any report into a hard failure even with the
        # default halt_on_error=0 (all reports print, then the run fails)
        "TSAN_OPTIONS": f"exitcode=66:suppressions={TSAN_SUPP}",
        # reduced burn: TSAN costs ~20x; 4x400 still crosses every
        # producer/consumer/ring-full interleaving the full test does
        "TBNET_STRESS_THREADS": "4",
        "TBNET_STRESS_N": "400",
        "SCHED_STRESS_THREADS": "4",
        "SCHED_STRESS_N": "200",
        "WSQ_STRESS_THREADS": "4",
        "WSQ_STRESS_N": "4000",
    }
    err = _preflight_native(env)
    if err:
        print(f"[FAIL] tsan: {err}")
        return 1
    rc, out = _pytest(TSAN_TESTS, env)
    bad = rc != 0 or "WARNING: ThreadSanitizer" in out
    tail = "\n".join(out.splitlines()[-15:])
    if bad:
        print(f"[FAIL] tsan ring + deque + scheduler stress:\n{tail}")
        return 1
    print(f"[ok] tsan ring + deque + scheduler stress: {_last_line(out)}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabriclint.san")
    ap.add_argument("--asan", action="store_true")
    ap.add_argument("--tsan", action="store_true")
    ap.add_argument(
        "--probe", action="store_true", help="report support and exit"
    )
    args = ap.parse_args(argv)
    if args.probe:
        for kind in ("asan", "tsan"):
            ok, detail = probe(kind)
            print(f"{kind}: {'supported' if ok else 'UNSUPPORTED'} ({detail})")
        return 0
    run_both = not (args.asan or args.tsan)
    rc = 0
    if args.asan or run_both:
        rc |= run_asan()
    if args.tsan or run_both:
        rc |= run_tsan()
    return rc


if __name__ == "__main__":
    sys.exit(main())
