"""tb_* error-code audit.

PR 4 found ``tb_server_register_native``'s return code silently
discarded — the method-index table could desynchronize from the C++
table and corrupt telemetry attribution.  That was found by hand; this
pass makes the class impossible: every call to a ``tb_*`` entry point
whose declared restype is an error indicator (``c_int`` / ``c_long`` —
the headers' 0/-errno/-1 convention) must USE the return value.  A call
appearing as a bare expression statement discards it; that is an
``ffi-unchecked`` violation unless the line carries an explicit

    # fabriclint: allow(ffi-unchecked) <why the code is meaningless here>

which is the "explicitly voided" form — the reason documents why (e.g.
closing a connection that is already being torn down, where a stale
token is the expected case, not an error).
"""

from __future__ import annotations

import ast
import ctypes
from typing import List, Optional, Set

from tools.fabriclint import (
    Violation,
    allowed,
    iter_py_files,
    scan_annotations,
)


def _error_returning() -> Set[str]:
    from incubator_brpc_tpu import native

    out: Set[str] = set()
    for name, (restype, _args) in native.SIGNATURES.items():
        if restype in (ctypes.c_int, ctypes.c_long):
            out.add(name)
    return out


def check_source(path: str, source: str) -> List[Violation]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    must_check = _error_returning()
    ann = scan_annotations(path, source)
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        fname = None
        if isinstance(call.func, ast.Attribute):
            fname = call.func.attr
        elif isinstance(call.func, ast.Name):
            fname = call.func.id
        if fname in must_check:
            if not allowed(ann, "ffi-unchecked", node.lineno):
                out.append(
                    Violation(
                        "ffi-unchecked", path, node.lineno,
                        f"{fname} returns an error code that is "
                        "discarded — check it, or void it with an "
                        "allow(ffi-unchecked) reason",
                    )
                )
    return out


def check(paths: Optional[List[str]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in (
        paths
        if paths is not None
        else iter_py_files(include_tests=True)
    ):
        with open(path, "r") as fh:
            source = fh.read()
        out.extend(check_source(path, source))
    return out
