"""Hot-path purity checker.

Functions marked with a ``# fabriclint: hotpath`` comment directly
above their ``def`` (or first decorator) sit on the native plane's
per-request or per-batch path: the telemetry drain batch, the frame
cut/dispatch shims, the limiter's ``on_responded``.  PR 4 measured what
Python-level per-record work costs there (~50% pump tax before the
drain was vectorized); this pass makes that class of regression a lint
failure instead of a bench regression two PRs later.

Inside a hotpath function the checker forbids:

- ``hotpath-lock`` — acquiring locks (``with ...lock``, ``.acquire()``,
  constructing ``threading.Lock``/``RLock``/``Condition``);
- ``hotpath-log`` — calls through ``logger``/``logging``;
- ``hotpath-io`` — ``print``/``open``/``input``, ``time.sleep``, and
  calls into ``os``/``subprocess``/``socket`` modules;
- ``hotpath-loop`` — any Python-level loop or comprehension: per-record
  iteration belongs in numpy (vectorized batch ops); a loop that is
  genuinely bounded by something small (distinct methods, decimated
  samples) carries an ``allow`` with the bound as the reason.

``except`` handler bodies are exempt wholesale — error paths are off
the hot path and may log/close freely.  Exemptions:
``# fabriclint: allow(<rule>) <reason>`` on the statement's first line
or the line above.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from tools.fabriclint import (
    Annotations,
    Violation,
    allowed,
    iter_py_files,
    scan_annotations,
)

_IO_NAMES = {"print", "open", "input"}
_IO_MODULES = {"os", "subprocess", "socket", "shutil"}
_LOG_NAMES = {"logger", "logging", "log"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def _attr_chain(node: ast.AST) -> List[str]:
    """['self', '_tel_lock'] for self._tel_lock; [] when not a chain."""

    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _looks_like_lock(expr: ast.AST) -> bool:
    chain = _attr_chain(expr)
    if chain and "lock" in chain[-1].lower():
        return True
    if isinstance(expr, ast.Call):
        c = _attr_chain(expr.func)
        if c and (c[-1] in _LOCK_CTORS or "lock" in c[-1].lower()):
            return True
    return False


class _HotpathVisitor(ast.NodeVisitor):
    def __init__(self, path: str, ann: Annotations):
        self.path = path
        self.ann = ann
        self.out: List[Violation] = []

    def _add(self, rule: str, node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", 1)
        if not allowed(self.ann, rule, line):
            self.out.append(Violation(rule, self.path, line, msg))

    # -- rules -------------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if _looks_like_lock(item.context_expr):
                src = ".".join(_attr_chain(item.context_expr)) or "<expr>"
                self._add(
                    "hotpath-lock", node,
                    f"lock acquisition on the hot path: with {src}",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        if chain:
            if chain[-1] == "acquire" and len(chain) > 1:
                self._add(
                    "hotpath-lock", node,
                    f"lock acquisition on the hot path: "
                    f"{'.'.join(chain)}()",
                )
            if chain[0] in _LOG_NAMES and len(chain) > 1:
                self._add(
                    "hotpath-log", node,
                    f"logging on the hot path: {'.'.join(chain)}()",
                )
            if len(chain) == 1 and chain[0] in _IO_NAMES:
                self._add(
                    "hotpath-io", node,
                    f"I/O on the hot path: {chain[0]}()",
                )
            if len(chain) > 1 and chain[0] in _IO_MODULES:
                self._add(
                    "hotpath-io", node,
                    f"I/O on the hot path: {'.'.join(chain)}()",
                )
            if chain[:2] == ["time", "sleep"]:
                self._add(
                    "hotpath-io", node, "time.sleep() on the hot path"
                )
            if (
                len(chain) == 2
                and chain[0] == "threading"
                and chain[1] in _LOCK_CTORS
            ):
                self._add(
                    "hotpath-lock", node,
                    f"lock construction on the hot path: "
                    f"{'.'.join(chain)}()",
                )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._add(
            "hotpath-loop", node,
            "Python-level loop on the hot path — vectorize over the "
            "batch, or allow() with the loop's bound as the reason",
        )
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._add(
            "hotpath-loop", node,
            "Python-level loop on the hot path — vectorize over the "
            "batch, or allow() with the loop's bound as the reason",
        )
        self.generic_visit(node)

    def _comp(self, node) -> None:
        self._add(
            "hotpath-loop", node,
            "Python-level comprehension on the hot path — vectorize, "
            "or allow() with the bound as the reason",
        )
        self.generic_visit(node)

    visit_ListComp = _comp
    visit_SetComp = _comp
    visit_DictComp = _comp
    visit_GeneratorExp = _comp

    # -- error paths are off the hot path ----------------------------------

    def visit_Try(self, node: ast.Try) -> None:
        for stmt in node.body + node.orelse + node.finalbody:
            self.visit(stmt)
        # handlers skipped: error paths may log/close/clean up freely


def _marked_functions(tree: ast.Module, marker_lines: Set[int]) -> list:
    """FunctionDefs whose def (or first decorator) sits directly under a
    ``# fabriclint: hotpath`` comment line."""

    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            first = node.lineno
            if node.decorator_list:
                first = min(d.lineno for d in node.decorator_list)
            if (first - 1) in marker_lines or first in marker_lines:
                out.append(node)
    return out


def check_source(path: str, source: str) -> List[Violation]:
    ann = scan_annotations(path, source)
    out: List[Violation] = list(ann.bad)
    if not ann.hotpath_lines:
        return out
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return out + [
            Violation("hotpath-loop", path, e.lineno or 1, "unparsable file")
        ]
    marked = _marked_functions(tree, set(ann.hotpath_lines))
    for fn in marked:
        visitor = _HotpathVisitor(path, ann)
        for stmt in fn.body:
            visitor.visit(stmt)
        out.extend(visitor.out)
    # a marker that doesn't sit above a def guards nothing — flag it so
    # a drive-by reformat can't silently detach the contract
    claimed = set()
    for fn in marked:
        first = fn.lineno
        if fn.decorator_list:
            first = min(d.lineno for d in fn.decorator_list)
        claimed.update({first - 1, first})
    for ln in ann.hotpath_lines:
        if ln not in claimed:
            out.append(
                Violation(
                    "bad-allow", path, ln,
                    "hotpath marker is not attached to a function "
                    "definition (put it on the line above the def)",
                )
            )
    return out


def check(paths: Optional[List[str]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in paths if paths is not None else iter_py_files():
        if os.path.basename(path) == "__main__.py" and "fabriclint" in path:
            continue
        with open(path, "r") as fh:
            source = fh.read()
        out.extend(check_source(path, source))
    return out
