"""CLI: ``python -m tools.fabriclint`` (half of the ``make lint`` entry
point; ``make lint`` merges this exit code with fabricverify's).

Runs all five passes over the repo and prints violations one per line
(``path:line: [rule] message``); exits 1 when any survive their
annotations.  ``--rule <name>`` filters the output to one rule family;
``--list-rules`` prints the rule ids; ``--json`` emits the shared
``{rule, file, line, reason}`` record array for CI diffing.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    from tools.fabriclint import RULES, run_all, to_records

    ap = argparse.ArgumentParser(prog="fabriclint")
    ap.add_argument("--rule", help="only report this rule id")
    ap.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit"
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit {rule, file, line, reason} records as a JSON array",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for r in RULES:
            print(r)
        return 0
    violations = run_all()
    if args.rule:
        violations = [v for v in violations if v.rule == args.rule]
    if args.json:
        print(json.dumps(to_records(violations), indent=2))
        return 1 if violations else 0
    for v in violations:
        print(v)
    if violations:
        print(f"fabriclint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("fabriclint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
