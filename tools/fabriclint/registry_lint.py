"""Flag & bvar registry lint.

Flags (``define_flag``): every defined flag must be *read* somewhere in
product code (``get_flag``/``flag_registry.get`` with the literal name,
through any import alias) and must carry help text.  A flag nobody
reads is configuration theater — the operator flips it and nothing
changes (``flag-dead``); a flag without help is unusable from the
``/flags`` service (``flag-undocumented``).

Bvars: every name exposed into the metrics registry must be a valid
identifier for the Prometheus exposition (dots tolerated — the
exposition sanitizes them), and the ``native_*``/``mc_*`` families must
appear in docs/OBSERVABILITY.md — those two prefixes are this repo's
documented contract for the native plane and the multi-controller
plane (``bvar-name``/``bvar-undocumented``).  Names built from
f-strings or concatenation are checked by their literal prefix (the
part before the first runtime placeholder).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.fabriclint import (
    REPO_ROOT,
    Violation,
    allowed,
    iter_py_files,
    scan_annotations,
)

OBSERVABILITY_MD = os.path.join(REPO_ROOT, "docs", "OBSERVABILITY.md")

_BVAR_CTORS = {
    "Adder",
    "Maxer",
    "Miner",
    "IntRecorder",
    "LatencyRecorder",
    "PassiveStatus",
    "Status",
    "Window",
    "PerSecond",
}

_PLACEHOLDER = "\x00"
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:.]*$")


def _str_template(node: ast.AST, local: Dict[str, str]) -> Optional[str]:
    """Resolve an expression to a string template where runtime parts
    become a placeholder byte; None when it is not string-shaped."""

    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append(_PLACEHOLDER)
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _str_template(node.left, local)
        right = _str_template(node.right, local)
        if left is None and right is None:
            return None
        return (left or _PLACEHOLDER) + (right or _PLACEHOLDER)
    if isinstance(node, ast.Name):
        return local.get(node.id)
    if isinstance(node, ast.Call):
        # "x".format(...) / name.replace(...) — runtime content
        return _PLACEHOLDER
    return None


# ---------------------------------------------------------------------------
# flags
# ---------------------------------------------------------------------------


def _flag_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(names bound to get_flag, names bound to define_flag) in a file."""

    gets, defs = {"get_flag"}, {"define_flag"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("utils.flags")
            or node.module.endswith("incubator_brpc_tpu.utils")
        ):
            for a in node.names:
                if a.name == "get_flag":
                    gets.add(a.asname or a.name)
                elif a.name == "define_flag":
                    defs.add(a.asname or a.name)
    return gets, defs


def _registry_method(node: ast.Call, method: str) -> bool:
    """True for ``flag_registry.<method>(...)`` specifically — a bare
    ``.get("name")``/``.define(...)`` on any other receiver is an
    ordinary dict/object call and must NOT count as a flag access
    (``sock.context.get("server")`` would otherwise mask a dead flag
    that happens to share a name with a dict key)."""

    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == method
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in ("flag_registry", "registry")
    )


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _first_str_arg(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
        node.args[0].value, str
    ):
        return node.args[0].value
    return None


def check_flags(paths: Optional[List[str]] = None) -> List[Violation]:
    product = [
        p
        for p in (paths if paths is not None else iter_py_files())
        if os.sep + "tools" + os.sep + "fabriclint" not in p
    ]
    defined: Dict[str, Tuple[str, int, bool]] = {}
    read: Set[str] = set()
    anns = {}
    for path in product:
        with open(path, "r") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        anns[path] = scan_annotations(path, source)
        gets, defs = _flag_aliases(tree)
        in_pkg = os.sep + "incubator_brpc_tpu" + os.sep in path
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cname = _call_name(node)
            arg = _first_str_arg(node)
            if arg is None:
                continue
            if cname in defs or _registry_method(node, "define"):
                if in_pkg:  # flags are a framework-level registry
                    has_help = any(
                        k.arg == "help" for k in node.keywords
                    ) or (
                        len(node.args) > 2
                        and isinstance(node.args[2], ast.Constant)
                        and isinstance(node.args[2].value, str)
                        and node.args[2].value.strip() != ""
                    )
                    defined.setdefault(arg, (path, node.lineno, has_help))
            elif cname in gets or _registry_method(node, "get"):
                read.add(arg)
    out: List[Violation] = []
    for name, (path, line, has_help) in sorted(defined.items()):
        ann = anns.get(path)
        if name not in read:
            if ann is None or not allowed(ann, "flag-dead", line):
                out.append(
                    Violation(
                        "flag-dead", path, line,
                        f"flag {name!r} is defined but never read "
                        "(get_flag) anywhere in product code",
                    )
                )
        if not has_help:
            if ann is None or not allowed(ann, "flag-undocumented", line):
                out.append(
                    Violation(
                        "flag-undocumented", path, line,
                        f"flag {name!r} has no help text — it is "
                        "unreadable from the /flags service",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# bvars
# ---------------------------------------------------------------------------


def _collect_bvar_names(
    tree: ast.Module,
) -> List[Tuple[str, int]]:
    """(name template, line) for every statically-visible exposure."""

    out: List[Tuple[str, int]] = []
    # local single-assignment string templates, resolved per function so
    # `base = "native_method_" + ...; recorder.expose(base)` is checked
    for fn in [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module))
    ]:
        local: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                t = _str_template(node.value, local)
                if t is not None:
                    local[node.targets[0].id] = t
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = _call_name(node)
            if cname in _BVAR_CTORS:
                for kw in node.keywords:
                    if kw.arg == "name":
                        t = _str_template(kw.value, local)
                        if t is not None:
                            out.append((t, node.lineno))
            elif cname == "expose" and node.args:
                t = _str_template(node.args[0], local)
                if t is not None:
                    out.append((t, node.lineno))
    # dedupe (module walk + function walks see nested nodes twice)
    return sorted(set(out), key=lambda x: x[1])


def check_bvars(paths: Optional[List[str]] = None) -> List[Violation]:
    with open(OBSERVABILITY_MD, "r") as fh:
        doc = fh.read()
    out: List[Violation] = []
    scope = [
        p
        for p in (paths if paths is not None else iter_py_files())
        if os.sep + "incubator_brpc_tpu" + os.sep in p
    ]
    for path in scope:
        with open(path, "r") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        ann = scan_annotations(path, source)
        for template, line in _collect_bvar_names(tree):
            probe = template.replace(_PLACEHOLDER, "x0")
            if not _NAME_RE.match(probe):
                if not allowed(ann, "bvar-name", line):
                    out.append(
                        Violation(
                            "bvar-name", path, line,
                            f"bvar name {template.replace(_PLACEHOLDER, '{}')!r}"
                            " is not a valid metric identifier "
                            "([a-zA-Z_:][a-zA-Z0-9_:.]*)",
                        )
                    )
                continue
            prefix = template.split(_PLACEHOLDER, 1)[0]
            display = template.replace(_PLACEHOLDER, "{}")
            if not (
                prefix.startswith("native_") or prefix.startswith("mc_")
            ):
                continue
            if _PLACEHOLDER not in template:
                documented = template in doc
                what = f"bvar {template!r}"
            else:
                # templated family: the literal prefix is the contract
                documented = len(prefix) >= 8 and prefix in doc
                what = f"bvar family {display!r} (prefix {prefix!r})"
            if not documented and not allowed(ann, "bvar-undocumented", line):
                out.append(
                    Violation(
                        "bvar-undocumented", path, line,
                        f"{what} follows the native_*/mc_* convention but "
                        "is not documented in docs/OBSERVABILITY.md",
                    )
                )
    return out


def check(paths: Optional[List[str]] = None) -> List[Violation]:
    return check_flags(paths) + check_bvars(paths)
