"""FFI-lifetime lint: callbacks crossing into C must be kept alive.

The classic ctypes crash: a ``CFUNCTYPE`` object passed to C is a
Python object like any other — if the only reference is the argument
expression, the GC collects it while C still holds the raw pointer, and
the next native callback jumps through freed memory.  It works in every
test (the GC hasn't run yet) and segfaults in production.

This pass finds every call to a ``tb_*`` entry point whose SIGNATURES
argtype is a CFUNCTYPE class and checks, structurally, that the
callback argument is a *retained* reference:

- a module-level binding (``@RELEASE_FN``-decorated function or a
  module-level ``X = CFUNCTYPE(...)`` assignment), or
- a ``self.<attr>`` the enclosing class assigns somewhere
  (``self._frame_cb = FRAME_FN(...)`` before registration).

Inline construction at the call site (``LIB.tb_server_set_frame_cb(s,
FRAME_FN(f), None)``) and locals that die with the frame are
violations (``ffi-keepalive``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.fabriclint import (
    Violation,
    allowed,
    iter_py_files,
    scan_annotations,
)


def _callback_positions() -> Dict[str, List[int]]:
    """tb_* function -> indices of CFUNCTYPE-typed arguments."""

    import ctypes

    from incubator_brpc_tpu import native

    out: Dict[str, List[int]] = {}
    for name, (_res, argtypes) in native.SIGNATURES.items():
        idxs = [
            i
            for i, t in enumerate(argtypes)
            if isinstance(t, type) and issubclass(t, ctypes._CFuncPtr)
        ]
        if idxs:
            out[name] = idxs
    return out


class _ClassAttrs(ast.NodeVisitor):
    """Map of class name -> attrs assigned via ``self.X = ...``."""

    def __init__(self) -> None:
        self.attrs: Dict[str, Set[str]] = {}
        self._stack: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.attrs.setdefault(node.name, set())
        self.generic_visit(node)
        self._stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._stack:
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    self.attrs[self._stack[-1]].add(tgt.attr)
        self.generic_visit(node)


def _module_level_names(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                names.add(a.asname or a.name.split(".")[0])
    return names


def check_source(path: str, source: str) -> List[Violation]:
    cb_pos = _callback_positions()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    ann = scan_annotations(path, source)
    out: List[Violation] = []
    module_names = _module_level_names(tree)
    cls_attrs = _ClassAttrs()
    cls_attrs.visit(tree)

    # enclosing class per call node: walk with a stack
    def _walk(node: ast.AST, cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            cls = node.name
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            fname = node.func.attr
            if fname in cb_pos:
                for i in cb_pos[fname]:
                    if i >= len(node.args):
                        continue
                    arg = node.args[i]
                    ok = False
                    what = ast.dump(arg)[:40]
                    if isinstance(arg, ast.Name):
                        ok = arg.id in module_names
                        what = arg.id
                    elif isinstance(arg, ast.Attribute):
                        what = arg.attr
                        if (
                            isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"
                        ):
                            ok = cls is not None and arg.attr in (
                                cls_attrs.attrs.get(cls, set())
                            )
                        else:
                            # module.attr is retained by the module; an
                            # attribute on a frame-local (holder.cb where
                            # holder dies with the frame) is NOT
                            ok = isinstance(
                                arg.value, ast.Name
                            ) and arg.value.id in module_names
                    if not ok and not allowed(
                        ann, "ffi-keepalive", node.lineno
                    ):
                        out.append(
                            Violation(
                                "ffi-keepalive", path, node.lineno,
                                f"{fname} callback argument {what!r} has "
                                "no keepalive binding — the GC can free "
                                "it while C still holds the pointer "
                                "(store it on self/module first)",
                            )
                        )
        for child in ast.iter_child_nodes(node):
            _walk(child, cls)

    _walk(tree, None)
    return out


def check(paths: Optional[List[str]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in (
        paths
        if paths is not None
        else iter_py_files(include_tests=True)
    ):
        with open(path, "r") as fh:
            source = fh.read()
        out.extend(check_source(path, source))
    return out
