"""FFI signature checker — the ctypes table vs. the compiler's truth.

``incubator_brpc_tpu/native.py`` declares every ``tb_*`` entry point's
restype/argtypes by hand (``native.SIGNATURES``); the C side declares
the same functions in src/tbutil/tbutil.h and src/tbnet/tbnet.h.  There
is no compiler on the seam: a drifted width, signedness, or argument
count does not fail to link — it silently truncates a 64-bit handle,
sign-extends an error code, or shifts every argument after the missing
one.  This checker parses the headers (tools/fabriclint/cdecl.py) and
diffs them against the live table:

- every sigs entry must match a header declaration in name, arity,
  integer width and signedness (``ffi-missing``/``ffi-arity``/
  ``ffi-type``);
- every header function must be bound (``ffi-unbound``) — an unbound
  function is an unchecked one the next PR will bind from memory;
- callback typedefs (``tb_frame_fn``...) must match their CFUNCTYPE
  mirrors field for field (``ffi-callback``);
- shared struct layouts (``tb_tbus_hdr``, ``tb_telemetry_record``,
  ``tb_ref_view``) must match their ctypes mirrors — offsets, widths,
  signedness, total size — and ``tb_telemetry_record`` additionally
  must match the numpy dtype the telemetry drain uses
  (``NativeServerPlane._rec_dtype``), the 48-byte ABI three ways
  (``ffi-struct``).
"""

from __future__ import annotations

import ast
import ctypes
import os
from typing import Dict, List, Optional, Tuple

from tools.fabriclint import (
    REPO_ROOT,
    Annotations,
    Violation,
    allowed,
    scan_annotations,
)
from tools.fabriclint import cdecl
from tools.fabriclint.cdecl import CType, Header

TBUTIL_H = os.path.join(REPO_ROOT, "src", "tbutil", "tbutil.h")
TBNET_H = os.path.join(REPO_ROOT, "src", "tbnet", "tbnet.h")
NATIVE_PY = os.path.join(REPO_ROOT, "incubator_brpc_tpu", "native.py")

# ctypes scalar class -> (bits, signed).  Aliases (c_uint32 is c_uint on
# LP64...) collapse by class identity.
_CTYPES_SCALARS = {
    ctypes.c_int8: (8, True),
    ctypes.c_uint8: (8, False),
    ctypes.c_int16: (16, True),
    ctypes.c_uint16: (16, False),
    ctypes.c_int32: (32, True),
    ctypes.c_uint32: (32, False),
    ctypes.c_int64: (64, True),
    ctypes.c_uint64: (64, False),
    ctypes.c_int: (32, True),
    ctypes.c_uint: (32, False),
    ctypes.c_long: (64, True),
    ctypes.c_ulong: (64, False),
    ctypes.c_size_t: (64, False),
    ctypes.c_ssize_t: (64, True),
}

# C struct name -> ctypes mirror attribute in incubator_brpc_tpu.native
_STRUCT_MIRRORS = {
    "tb_tbus_hdr": "TbusHdr",
    "tb_telemetry_record": "TelemetryRecord",
    "tb_ref_view": "_Ref",
}

# header callback typedef -> (module, attribute) of the CFUNCTYPE mirror
_FUNCPTR_MIRRORS = {
    "tb_release_fn": ("incubator_brpc_tpu.native", "RELEASE_FN"),
    "tb_frame_fn": ("incubator_brpc_tpu.native", "FRAME_FN"),
    "tb_handoff_fn": ("incubator_brpc_tpu.native", "HANDOFF_FN"),
    "tb_closed_fn": ("incubator_brpc_tpu.native", "CLOSED_FN"),
    "tb_native_fn": (
        "incubator_brpc_tpu.transport.native_plane",
        "NATIVE_METHOD_FN",
    ),
    "tb_auth_fn": ("incubator_brpc_tpu.native", "AUTH_FN"),
}


def _is_cfunctype(t) -> bool:
    return isinstance(t, type) and issubclass(t, ctypes._CFuncPtr)


def _is_pointer(t) -> bool:
    return isinstance(t, type) and issubclass(t, ctypes._Pointer)


def _is_structure(t) -> bool:
    return isinstance(t, type) and issubclass(t, ctypes.Structure)


def _scalar_of(t) -> Optional[Tuple[int, bool]]:
    return _CTYPES_SCALARS.get(t)


def _pyname(t) -> str:
    if t is None:
        return "None"
    return getattr(t, "__name__", repr(t))


def _match(py, c: CType, merged: Header) -> Optional[str]:
    """None when the ctypes declaration can faithfully carry the C type;
    otherwise a human-readable mismatch description."""

    if c.kind == "void":
        return None if py is None else f"C void vs ctypes {_pyname(py)}"
    if py is None:
        return f"C {c} vs ctypes None (restype void)"
    if c.kind == "scalar":
        sc = _scalar_of(py)
        if sc is None:
            return f"C {c} vs non-scalar ctypes {_pyname(py)}"
        bits, signed_ = sc
        if bits != c.bits:
            return f"width: C {c} vs ctypes {_pyname(py)} ({bits} bits)"
        if signed_ != c.signed_:
            return (
                f"signedness: C {c} vs ctypes {_pyname(py)} "
                f"({'signed' if signed_ else 'unsigned'})"
            )
        return None
    # c.kind == "ptr"
    if py is ctypes.c_void_p:
        if c.pointee.startswith("fn:"):
            return (
                f"C callback {c} passed as c_void_p — layout unchecked "
                "(annotate if the cast is by design)"
            )
        if c.pointee.startswith("scalar:") or c.pointee.startswith("struct:"):
            return (
                f"C {c} vs bare c_void_p — use a typed POINTER so width "
                "and layout stay checked"
            )
        return None  # void*/char*/opaque handles travel as c_void_p
    if py is ctypes.c_char_p:
        if c.pointee in ("void", "char"):
            return None
        return f"C {c} vs c_char_p"
    if _is_cfunctype(py):
        if not c.pointee.startswith("fn:"):
            return f"C {c} vs ctypes callback {_pyname(py)}"
        tdname = c.pointee[3:]
        td = merged.funcptrs.get(tdname)
        if td is None:
            return f"unknown callback typedef {tdname}"
        return _match_cfunctype(py, td, merged)
    if _is_pointer(py):
        inner = py._type_
        if c.pointee.startswith("scalar:"):
            want = cdecl.SCALARS.get(c.pointee[7:])
            got = _scalar_of(inner)
            if got is None:
                return f"C {c} vs POINTER({_pyname(inner)})"
            if want != got:
                return (
                    f"pointee: C {c} vs POINTER({_pyname(inner)}) "
                    f"({got[0]} bits, {'signed' if got[1] else 'unsigned'})"
                )
            return None
        if c.pointee.startswith("struct:"):
            cname = c.pointee[7:]
            if not _is_structure(inner):
                return f"C {c} vs POINTER({_pyname(inner)})"
            want_attr = _STRUCT_MIRRORS.get(cname)
            if want_attr is not None and inner.__name__ != want_attr:
                return (
                    f"C {c} vs POINTER({_pyname(inner)}) — expected the "
                    f"{want_attr} mirror"
                )
            return None  # layout itself is checked once, globally
        if c.pointee == "ptr":
            if inner is ctypes.c_char_p or inner is ctypes.c_void_p:
                return None
            return f"C pointer-to-pointer vs POINTER({_pyname(inner)})"
        return f"C {c} vs POINTER({_pyname(inner)})"
    return f"C {c} vs ctypes {_pyname(py)}"


def _match_cfunctype(py, td, merged: Header) -> Optional[str]:
    """Compare a CFUNCTYPE class against a header fn-ptr typedef."""

    res = getattr(py, "_restype_", None)
    args = list(getattr(py, "_argtypes_", ()) or ())
    err = _match(res, td.ret, merged)
    if err is not None:
        return f"callback {td.name} return: {err}"
    if len(args) != len(td.args):
        return (
            f"callback {td.name} arity: C has {len(td.args)} args, "
            f"CFUNCTYPE has {len(args)}"
        )
    for i, (pa, ca) in enumerate(zip(args, td.args)):
        err = _match(pa, ca, merged)
        if err is not None:
            return f"callback {td.name} arg {i}: {err}"
    return None


def _sig_entry_lines(source: str) -> Dict[str, int]:
    """Line number of each SIGNATURES dict key in native.py."""

    out: Dict[str, int] = {}
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "SIGNATURES":
                    if isinstance(node.value, ast.Dict):
                        for k in node.value.keys:
                            if isinstance(k, ast.Constant) and isinstance(
                                k.value, str
                            ):
                                out[k.value] = k.lineno
    return out


def _check_struct_ctypes(
    cs: cdecl.CStruct, mirror, path: str
) -> List[Violation]:
    out: List[Violation] = []
    pyfields = getattr(mirror, "_fields_", [])
    if len(pyfields) != len(cs.fields):
        out.append(
            Violation(
                "ffi-struct", path, cs.line,
                f"{cs.name}: C has {len(cs.fields)} fields, "
                f"{mirror.__name__} has {len(pyfields)}",
            )
        )
        return out
    for cf, (pyname, pytype) in zip(cs.fields, pyfields):
        desc = getattr(mirror, pyname)
        if pyname != cf.name:
            out.append(
                Violation(
                    "ffi-struct", path, cs.line,
                    f"{cs.name}.{cf.name}: mirror field is named {pyname}",
                )
            )
            continue
        if cf.is_ptr:
            ok = pytype in (ctypes.c_void_p, ctypes.c_char_p) or _is_pointer(
                pytype
            )
            if not ok:
                out.append(
                    Violation(
                        "ffi-struct", path, cs.line,
                        f"{cs.name}.{cf.name}: C pointer vs "
                        f"{_pyname(pytype)}",
                    )
                )
                continue
        else:
            sc = _scalar_of(pytype)
            if sc is None or sc != (cf.bits, cf.signed_):
                out.append(
                    Violation(
                        "ffi-struct", path, cs.line,
                        f"{cs.name}.{cf.name}: C "
                        f"{'i' if cf.signed_ else 'u'}{cf.bits} vs "
                        f"{_pyname(pytype)}",
                    )
                )
                continue
        if desc.offset * 8 != cf.offset_bits or desc.size * 8 != cf.bits:
            out.append(
                Violation(
                    "ffi-struct", path, cs.line,
                    f"{cs.name}.{cf.name}: offset/size "
                    f"{desc.offset}/{desc.size} bytes vs C "
                    f"{cf.offset_bits // 8}/{cf.bits // 8}",
                )
            )
    if ctypes.sizeof(mirror) * 8 != cs.size_bits:
        out.append(
            Violation(
                "ffi-struct", path, cs.line,
                f"{cs.name}: sizeof mismatch — C {cs.size_bits // 8} "
                f"bytes, ctypes {ctypes.sizeof(mirror)}",
            )
        )
    return out


def _check_telemetry_dtype(cs: cdecl.CStruct, path: str) -> List[Violation]:
    """The numpy structured dtype the drain overlays on the batch buffer
    is a THIRD copy of the record ABI — check it against the header too."""

    out: List[Violation] = []
    from incubator_brpc_tpu.transport.native_plane import NativeServerPlane

    dt = NativeServerPlane._rec_dtype()
    if dt.itemsize * 8 != cs.size_bits:
        out.append(
            Violation(
                "ffi-struct", path, cs.line,
                f"{cs.name}: numpy dtype itemsize {dt.itemsize} vs C "
                f"{cs.size_bits // 8} bytes",
            )
        )
    names = list(dt.names or ())
    if names != [f.name for f in cs.fields]:
        out.append(
            Violation(
                "ffi-struct", path, cs.line,
                f"{cs.name}: numpy dtype fields {names} vs C "
                f"{[f.name for f in cs.fields]}",
            )
        )
        return out
    for cf in cs.fields:
        sub, offset = dt.fields[cf.name][:2]
        if (
            offset * 8 != cf.offset_bits
            or sub.itemsize * 8 != cf.bits
            or sub.kind != ("i" if cf.signed_ else "u")
            or sub.byteorder not in ("<", "=", "|")
        ):
            out.append(
                Violation(
                    "ffi-struct", path, cs.line,
                    f"{cs.name}.{cf.name}: numpy {sub.str}@{offset} vs C "
                    f"{'i' if cf.signed_ else 'u'}{cf.bits}"
                    f"@{cf.offset_bits // 8}",
                )
            )
    return out


def parse_repo_headers(
    tbutil_text: Optional[str] = None, tbnet_text: Optional[str] = None
) -> Header:
    tu = cdecl.parse_header(TBUTIL_H, text=tbutil_text)
    tn = cdecl.parse_header(TBNET_H, text=tbnet_text, base=tu)
    return cdecl.merge_headers([tu, tn])


def check(
    tbutil_text: Optional[str] = None,
    tbnet_text: Optional[str] = None,
    signatures: Optional[dict] = None,
) -> List[Violation]:
    """Cross-check SIGNATURES against the headers.  The text/signature
    injection points exist for the meta-tests (seeded mutations must
    flip the checker red)."""

    from incubator_brpc_tpu import native

    tbutil_hdr = cdecl.parse_header(TBUTIL_H, text=tbutil_text)
    tbnet_hdr = cdecl.parse_header(TBNET_H, text=tbnet_text, base=tbutil_hdr)
    merged = cdecl.merge_headers([tbutil_hdr, tbnet_hdr])
    sigs = native.SIGNATURES if signatures is None else signatures
    with open(NATIVE_PY, "r") as fh:
        native_src = fh.read()
    sig_lines = _sig_entry_lines(native_src)
    native_ann = scan_annotations(NATIVE_PY, native_src)
    header_anns = {
        TBUTIL_H: scan_annotations(TBUTIL_H, tbutil_text),
        TBNET_H: scan_annotations(TBNET_H, tbnet_text),
    }
    out: List[Violation] = list(native_ann.bad)
    for ann in header_anns.values():
        out.extend(ann.bad)

    def _hdr_allowed(rule: str, line: int, path: str) -> bool:
        ann = header_anns.get(path)
        return ann is not None and allowed(ann, rule, line)

    for hdr, path in ((tbutil_hdr, TBUTIL_H), (tbnet_hdr, TBNET_H)):
        for line, decl in hdr.unparsed:
            out.append(
                Violation(
                    "ffi-parse", path, line,
                    f"declaration not modeled by the checker: {decl[:80]}",
                )
            )

    for name, (restype, argtypes) in sigs.items():
        line = sig_lines.get(name, 1)
        cf = merged.funcs.get(name)
        if cf is None:
            if not allowed(native_ann, "ffi-missing", line):
                out.append(
                    Violation(
                        "ffi-missing", NATIVE_PY, line,
                        f"{name} is declared in SIGNATURES but not in any "
                        "header",
                    )
                )
            continue
        err = _match(restype, cf.ret, merged)
        if err is not None:
            rule = "ffi-callback" if "callback" in err else "ffi-type"
            if not allowed(native_ann, rule, line):
                out.append(
                    Violation(rule, NATIVE_PY, line, f"{name} return: {err}")
                )
        if len(argtypes) != len(cf.args):
            if not allowed(native_ann, "ffi-arity", line):
                out.append(
                    Violation(
                        "ffi-arity", NATIVE_PY, line,
                        f"{name}: C has {len(cf.args)} args, SIGNATURES "
                        f"has {len(argtypes)}",
                    )
                )
            continue
        for i, (pa, ca) in enumerate(zip(argtypes, cf.args)):
            err = _match(pa, ca, merged)
            if err is not None:
                rule = (
                    "ffi-callback"
                    if "callback" in err or ca.pointee.startswith("fn:")
                    else "ffi-type"
                )
                if not allowed(native_ann, rule, line):
                    out.append(
                        Violation(
                            rule, NATIVE_PY, line, f"{name} arg {i}: {err}"
                        )
                    )

    for name, cf in merged.funcs.items():
        if name not in sigs:
            src_path = TBUTIL_H if name in tbutil_hdr.funcs else TBNET_H
            if not _hdr_allowed("ffi-unbound", cf.line, src_path):
                out.append(
                    Violation(
                        "ffi-unbound", src_path, cf.line,
                        f"{name} is exported by the header but has no "
                        "SIGNATURES entry",
                    )
                )

    # callback typedef layouts (checked globally, not only at use sites)
    for tdname, (mod, attr) in _FUNCPTR_MIRRORS.items():
        td = merged.funcptrs.get(tdname)
        if td is None:
            out.append(
                Violation(
                    "ffi-callback", TBNET_H, 1,
                    f"callback typedef {tdname} not found in headers",
                )
            )
            continue
        import importlib

        py = getattr(importlib.import_module(mod), attr)
        err = _match_cfunctype(py, td, merged)
        if err is not None:
            out.append(
                Violation(
                    "ffi-callback", TBNET_H, td.line, f"{attr}: {err}"
                )
            )

    # struct layouts: header vs ctypes mirror (and numpy for telemetry)
    for cname, attr in _STRUCT_MIRRORS.items():
        cs = merged.structs.get(cname)
        if cs is None:
            out.append(
                Violation(
                    "ffi-struct", TBNET_H, 1,
                    f"struct {cname} not found in headers",
                )
            )
            continue
        mirror = getattr(native, attr)
        src_path = TBUTIL_H if cname in tbutil_hdr.structs else TBNET_H
        out.extend(_check_struct_ctypes(cs, mirror, src_path))
        if cname == "tb_telemetry_record":
            out.extend(_check_telemetry_dtype(cs, src_path))
    return out
