#!/usr/bin/env python
"""rpc_press — load generator for tbus_std servers (reference
tools/rpc_press: drives a method at a target qps/concurrency and reports
qps + latency percentiles).

Usage:
    python tools/rpc_press.py --server 127.0.0.1:8000 \
        --method demo.echo --payload-bytes 64 --threads 8 --duration 5
"""

from __future__ import annotations

import argparse
import sys
import threading
import time


def _trace_schedule(trace_sample_rate: float):
    """Counter-scheduled trace stamping (exact-rate, like the fault
    seam): returns ``(every, trace_id, next_span_id)`` — every
    ``every``'th operation carries the run's trace id, a fresh span id,
    and the head-based ``sampled`` bit, so one command produces a
    coherent fleet-observable traced flood.  ``every`` is 0 when the
    rate is 0 (untraced run)."""
    import itertools
    import random

    if trace_sample_rate <= 0:
        return 0, 0, None
    every = max(1, round(1.0 / trace_sample_rate))
    trace_id = random.getrandbits(63) | 1
    counter = itertools.count()

    def next_span_id():
        # one shared counter across every worker thread: seq % every == 0
        # elects, seq + 1 is the traced call's distinct span id
        seq = next(counter)
        return (seq + 1) if seq % every == 0 else 0

    return every, trace_id, next_span_id


def run_press(
    server: str,
    service: str,
    method: str,
    payload: bytes,
    threads: int = 4,
    duration: float = 5.0,
    timeout_ms: float = 1000,
    transport: str = "tcp",
    native_plane: bool = False,
    fault_rate: float = 0.0,
    fault_delay_ms: float = 0.0,
    compress_type: str = "",
    auth_token: str = "",
    trace_sample_rate: float = 0.0,
) -> dict:
    from incubator_brpc_tpu.bvar import LatencyRecorder
    from incubator_brpc_tpu.rpc import (
        Channel,
        ChannelOptions,
        Controller,
        TokenAuthenticator,
    )

    if fault_rate > 0 or fault_delay_ms > 0:
        # one-command brownout run: arm the deterministic fault seam of
        # WHICHEVER plane carries the traffic. Python plane: the
        # FaultInjector at the Socket.write seam (rpc/fault_injector.py).
        # Native plane: the tb_channel_set_fault counter schedule — every
        # round(1/rate)'th call fails/delays in C++, so --native-plane
        # brownout runs no longer force the interpreter onto the path
        # (PR 3's documented workaround, retired). Both live behind the
        # same fault_injection master flag.
        from incubator_brpc_tpu.utils.flags import set_flag_unchecked

        set_flag_unchecked("fault_injection", True)
        if native_plane:
            from incubator_brpc_tpu.transport.native_plane import (
                install_native_client_fault,
            )

            install_native_client_fault(
                fail_every=(
                    max(1, round(1.0 / fault_rate)) if fault_rate > 0 else 0
                ),
                delay_every=1 if fault_delay_ms > 0 else 0,
                delay_ms=int(fault_delay_ms),
            )
            print(
                "native-plane fault seam armed (counter schedule: "
                f"fail every {max(1, round(1.0 / fault_rate)) if fault_rate > 0 else 0}"
                f", delay {fault_delay_ms:g} ms/call)",
                file=sys.stderr,
            )
        else:
            from incubator_brpc_tpu.rpc import (
                FaultInjector,
                install_socket_injector,
            )

            install_socket_injector(
                FaultInjector(
                    error_rate=fault_rate,
                    delay_rate=1.0 if fault_delay_ms > 0 else 0.0,
                    delay_ms=fault_delay_ms,
                )
            )

    # compressed/authenticated floods drive the NATIVE client seam when
    # --native-plane is set: the credential and compress_type stamp the
    # PRPC meta in C++ (baidu_std is the protocol that carries both), so
    # one command floods a native target with production-shaped frames
    proto = "baidu_std" if (compress_type or auth_token) else "tbus_std"
    ch = Channel()
    if not ch.init(
        server,
        options=ChannelOptions(
            timeout_ms=timeout_ms,
            transport=transport,
            native_plane=native_plane,
            protocol=proto,
            auth=TokenAuthenticator([auth_token]) if auth_token else None,
        ),
    ):
        raise SystemExit(f"cannot init channel to {server}")

    # counter-scheduled traced flood: every Nth call carries the run's
    # trace id + a fresh span id + the head-based sampled bit — a traced
    # flood is one command, and the whole run is one fleet-assemblable
    # trace (rpc_view --trace <id> --targets ...)
    trace_every, run_trace_id, next_span_id = _trace_schedule(
        trace_sample_rate
    )
    if trace_every:
        print(
            f"traced flood: every {trace_every}th call carries "
            f"trace {run_trace_id:x} (sampled bit set)",
            file=sys.stderr,
        )
    latency = LatencyRecorder(name=None)
    stop_at = time.monotonic() + duration
    counts = {"ok": 0, "fail": 0}
    lock = threading.Lock()

    def worker():
        ok = fail = 0
        while time.monotonic() < stop_at:
            t0 = time.perf_counter()
            cntl = None
            if compress_type:
                cntl = Controller()
                cntl.compress_type = compress_type
            if trace_every:
                span = next_span_id()
                if span:
                    cntl = cntl or Controller()
                    cntl.trace_id = run_trace_id
                    cntl.span_id = span
                    cntl.trace_sampled = 1
            cntl = ch.call_method(service, method, payload, cntl=cntl)
            if cntl.ok():
                ok += 1
                latency << (time.perf_counter() - t0) * 1e6
            else:
                fail += 1
        with lock:
            counts["ok"] += ok
            counts["fail"] += fail

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.monotonic() - t0
    return {
        "qps": counts["ok"] / wall if wall else 0.0,
        "ok": counts["ok"],
        "fail": counts["fail"],
        "latency_us_avg": latency.latency(),
        "latency_us_p50": latency.latency_percentile(0.5),
        "latency_us_p99": latency.latency_percentile(0.99),
        "latency_us_max": latency.max_latency(),
        "trace_id": run_trace_id,
    }


def run_reactor_press(
    server: str,
    service: str,
    method: str,
    payload: bytes,
    reactors: int,
    conns_per_reactor: int = 2,
    duration: float = 5.0,
    timeout_ms: float = 1000,
    fault_rate: float = 0.0,
    fault_delay_ms: float = 0.0,
    compress_type: str = "",
    auth_token: str = "",
    trace_sample_rate: float = 0.0,
) -> dict:
    """Sharded-accept load run: ``reactors * conns_per_reactor`` native
    client channels (each pinned to its own client reactor shard at
    connect) flood the target concurrently, then the target's
    ``/vars`` is scraped for the ``native_reactor_<port>_<i>_conns``
    family so the per-reactor connection distribution — and any skew in
    the accept sharding — is printed next to the qps numbers.  The
    ``--fault-rate``/``--fault-delay-ms`` brownout flags arm the native
    client fault seam (tb_channel_set_fault) on every channel, exactly
    like ``--native-plane`` runs."""
    import re

    from incubator_brpc_tpu.bvar import LatencyRecorder
    from incubator_brpc_tpu.transport.native_plane import (
        NET_AVAILABLE,
        NativeClientChannel,
        install_native_client_fault,
    )

    if not NET_AVAILABLE:
        raise SystemExit("--reactors needs the native plane (libtbutil.so)")
    if fault_rate > 0 or fault_delay_ms > 0:
        from incubator_brpc_tpu.utils.flags import set_flag_unchecked

        set_flag_unchecked("fault_injection", True)
        install_native_client_fault(
            fail_every=(
                max(1, round(1.0 / fault_rate)) if fault_rate > 0 else 0
            ),
            delay_every=1 if fault_delay_ms > 0 else 0,
            delay_ms=int(fault_delay_ms),
        )
        print(
            "native-plane fault seam armed on every reactor channel "
            f"(fail every "
            f"{max(1, round(1.0 / fault_rate)) if fault_rate > 0 else 0}, "
            f"delay {fault_delay_ms:g} ms/call)",
            file=sys.stderr,
        )
    ip, _, port = server.rpartition(":")
    nconns = max(1, reactors) * max(1, conns_per_reactor)
    # compressed/authenticated floods speak baidu_std (the protocol that
    # carries compress_type/authentication_data) on the NATIVE client
    # seam: the payload compresses ONCE here, the credential and codec id
    # stamp every frame's RpcMeta in C++
    production = bool(compress_type or auth_token)
    proto = "baidu_std" if production else "tbus_std"
    chans = [
        NativeClientChannel(ip, int(port), protocol=proto)
        for _ in range(nconns)
    ]
    if production:
        from incubator_brpc_tpu.protocol import compress as compress_mod

        if compress_type:
            payload = compress_mod.compress(compress_type, payload)
        for ch in chans:
            if auth_token:
                ch.set_auth(auth_token)
            if compress_type:
                ch.set_request_compress(compress_type)
    # traced floods on the REACTOR path stamp the native client seam
    # directly: the trace fields ride each traced call's RpcRequestMeta
    # (or tbus JSON meta) and the server's C++ cutter keeps them on the
    # fast path — same counter schedule as the plain path
    trace_every, run_trace_id, next_span_id = _trace_schedule(
        trace_sample_rate
    )
    if trace_every:
        print(
            f"traced flood: every {trace_every}th call carries "
            f"trace {run_trace_id:x} (sampled bit set)",
            file=sys.stderr,
        )
    latency = LatencyRecorder(name=None)
    stop_at = time.monotonic() + duration
    counts = {"ok": 0, "fail": 0}
    lock = threading.Lock()

    def worker(ch):
        ok = fail = 0
        while time.monotonic() < stop_at:
            t0 = time.perf_counter()
            span = next_span_id() if trace_every else 0
            rc, err, _meta, _body = ch.call(
                service, method, payload, timeout_ms=int(timeout_ms),
                trace_id=run_trace_id if span else 0,
                span_id=span, sampled=1 if span else 0,
            )
            if rc >= 0 and err == 0:
                ok += 1
                latency << (time.perf_counter() - t0) * 1e6
            else:
                fail += 1
        with lock:
            counts["ok"] += ok
            counts["fail"] += fail

    ts = [threading.Thread(target=worker, args=(ch,)) for ch in chans]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.monotonic() - t0
    # scrape the distribution while our connections are still open — a
    # closed channel leaves the reactor's conn gauge before we read it
    distribution = {}
    try:
        text = _http_get(server, "/vars", timeout=2.0)
        # anchored to THIS port: a process serving several native ports
        # exposes a native_reactor_* family per port, and merging them
        # would misreport the very skew this print exists to surface
        for m in re.finditer(
            rf"native_reactor_{int(port)}_(\d+)_conns\s*:\s*(\d+)", text
        ):
            distribution[int(m.group(1))] = int(m.group(2))
    except OSError:
        pass  # no portal reachable: fall back to client-side pins below
    misroutes = sum(ch.cid_misroutes() for ch in chans)
    client_shards = [ch.reactor for ch in chans]
    for ch in chans:
        ch.close()
    return {
        "qps": counts["ok"] / wall if wall else 0.0,
        "ok": counts["ok"],
        "fail": counts["fail"],
        "latency_us_avg": latency.latency(),
        "latency_us_p50": latency.latency_percentile(0.5),
        "latency_us_p99": latency.latency_percentile(0.99),
        "latency_us_max": latency.max_latency(),
        "reactor_conns": distribution,
        "client_shards": client_shards,
        "cid_misroutes": misroutes,
        "trace_id": run_trace_id,
    }


def _http_get(server: str, path: str, timeout: float = 5.0) -> str:
    """One ad-hoc HTTP GET against the target's builtin portal (every
    server serves it on its RPC port).  Servers may hold the connection
    open after the response (keep-alive on handed-off native
    connections), so the read stops once Content-Length is satisfied; a
    timeout is tolerated ONLY for a complete (or length-less) body — a
    server stalling mid-body still raises instead of returning silently
    truncated output."""
    import re as _re
    import socket as _socket

    ip, _, port = server.rpartition(":")
    with _socket.create_connection((ip, int(port)), timeout=timeout) as s:
        s.sendall(
            f"GET {path} HTTP/1.0\r\nHost: {server}\r\n\r\n".encode()
        )
        out = b""
        expect = None  # total bytes once headers + Content-Length known
        while expect is None or len(out) < expect:
            if expect is None and b"\r\n\r\n" in out:
                head, _, _rest = out.partition(b"\r\n\r\n")
                m = _re.search(
                    rb"^content-length:\s*(\d+)\s*$", head,
                    _re.IGNORECASE | _re.MULTILINE,
                )
                if m:
                    expect = len(head) + 4 + int(m.group(1))
                    continue
            try:
                chunk = s.recv(4096)
            except _socket.timeout:
                if expect is None and out:
                    break  # no Content-Length: best effort, data in hand
                raise  # nothing yet, or a server stalled mid-body
            if not chunk:
                break
            out += chunk
    return out.decode(errors="replace")


def run_lame_duck_drill(
    server: str,
    service: str,
    method: str,
    payload: bytes,
    threads: int = 4,
    duration: float = 5.0,
    timeout_ms: float = 1000,
    grace_s: float = 0.0,
) -> dict:
    """Drain-under-load in one command: flood the target, trigger its
    ``/quitquitquit`` builtin a third of the way in, keep pressing until
    the server is gone, and classify what the clients saw.  A clean
    lame-duck drain shows ZERO connection-reset-class failures: in-flight
    RPCs finish, refreshed work gets retriable ELOGOFF, and only after
    the drain completes do connects start being refused (not counted —
    the workers stop at the first connect-refused-class error after the
    trigger)."""
    import threading as _threading

    from incubator_brpc_tpu.rpc import Channel, ChannelOptions
    from incubator_brpc_tpu.utils.status import ErrorCode

    grace = grace_s if grace_s > 0 else max(1.0, duration * 0.5)
    ch = Channel()
    if not ch.init(
        server, options=ChannelOptions(timeout_ms=timeout_ms, max_retry=0)
    ):
        raise SystemExit(f"cannot init channel to {server}")
    RESET_CODES = frozenset(
        {ErrorCode.EFAILEDSOCKET, ErrorCode.EEOF, ErrorCode.ECLOSE}
    )
    events = []  # (issue time, completion time, kind) across every worker
    lock = _threading.Lock()
    triggered = _threading.Event()
    stop_at = time.monotonic() + duration

    def worker():
        local = []
        while time.monotonic() < stop_at:
            issued = time.monotonic()
            cntl = ch.call_method(service, method, payload)
            code = cntl.error_code
            now = time.monotonic()
            if code == 0:
                local.append((issued, now, "ok"))
            elif code == ErrorCode.ELOGOFF:
                local.append((issued, now, "logoff"))
            elif code in RESET_CODES or code == ErrorCode.EHOSTDOWN:
                local.append((issued, now, "conn"))
                if triggered.is_set():
                    break  # the server is gone: the drill is over
            else:
                local.append((issued, now, "other"))
        with lock:
            events.extend(local)

    ts = [_threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    time.sleep(duration * 0.3)
    print(
        f"triggering /quitquitquit?grace_s={grace:g} on {server}",
        file=sys.stderr,
    )
    status = _http_get(server, f"/quitquitquit?grace_s={grace:g}")
    triggered.set()
    status_line = status.splitlines()[0] if status else "<empty response>"
    if " 200 " not in status_line:
        print(f"quitquitquit answered: {status_line}", file=sys.stderr)
        if " 403 " in status_line:
            print(
                "hint: the target must run with the enable_quitquitquit "
                "flag on (default off)",
                file=sys.stderr,
            )
        # no drain was triggered: stop the flood and report the refusal
        # instead of classifying a drill that never ran
        for t in ts:
            t.join()
        return {
            "ok": 0, "logoff": 0, "reset": 0, "other": 0,
            "drained_clean": False, "trigger_failed": status_line,
        }
    for t in ts:
        t.join()
    # Classification: a RESET is a connection-class failure of a call
    # that was ISSUED while the server was still serving (issue time
    # comfortably before the last served ok/ELOGOFF) — that is admitted
    # or admissible work killed mid-drain, including a grace-expiry hard
    # stop cutting off slow in-flight handlers.  Connection failures of
    # calls issued AT the very end of the serving window (within the
    # guard band) are the shutdown boundary: the final close racing the
    # last writes, or connects refused on the now-stopped server — the
    # drill ending, not dirty draining.
    GUARD_S = 0.05
    served = [done for _i, done, k in events if k in ("ok", "logoff")]
    last_served = max(served) if served else 0.0
    counts = {
        "ok": sum(1 for _i, _d, k in events if k == "ok"),
        "logoff": sum(1 for _i, _d, k in events if k == "logoff"),
        "reset": sum(
            1
            for issued, _d, k in events
            if k == "conn" and issued < last_served - GUARD_S
        ),
        "other": sum(1 for _i, _d, k in events if k == "other"),
    }
    counts["drained_clean"] = counts["reset"] == 0 and counts["other"] == 0
    return counts


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--server", required=True, help="ip:port or naming url")
    p.add_argument("--method", required=True, help="service.method")
    p.add_argument("--payload-bytes", type=int, default=64)
    p.add_argument("--payload-file", help="read request payload from a file")
    p.add_argument("--threads", type=int, default=4)
    p.add_argument("--duration", type=float, default=5.0, help="seconds")
    p.add_argument("--timeout-ms", type=float, default=1000)
    p.add_argument(
        "--transport", choices=("tcp", "tpu"), default="tcp",
        help="tpu = drive the load over device links (the rdma_performance "
        "client's use_rdma flag)",
    )
    p.add_argument(
        "--native-plane", action="store_true",
        help="route eligible calls through the C++ client channel",
    )
    p.add_argument(
        "--reactors", type=int, default=0,
        help="sharded-accept load: open REACTORS * CONNS_PER_REACTOR "
        "native channels (each pinned to its own client reactor shard) "
        "and print the server's per-reactor connection distribution so "
        "skewed sharding is visible",
    )
    p.add_argument(
        "--conns-per-reactor", type=int, default=2,
        help="connections per reactor group for --reactors runs",
    )
    p.add_argument(
        "--compress-type", choices=("none", "snappy", "gzip", "zlib1"),
        default="none",
        help="compress request payloads with this codec (baidu_std wire "
        "compress_type; with --native-plane or --reactors the flood rides "
        "the C++ client seam end to end — compressed once, stamped per "
        "frame in C++)",
    )
    p.add_argument(
        "--auth-token", default="",
        help="authenticate the flood with this bearer token "
        "(authentication_data on the first request per connection; pair "
        "with a server running TokenAuthenticator)",
    )
    p.add_argument(
        "--trace-sample-rate", type=float, default=0.0,
        help="stamp trace context (run trace id, fresh span id, the "
        "head-based sampled bit) on this fraction of calls — "
        "counter-scheduled exact rate like the fault seam, on both the "
        "plain and --reactors load paths; the run's trace id is printed "
        "so `rpc_view --trace <id> --targets ...` can assemble it",
    )
    p.add_argument(
        "--fault-rate", type=float, default=0.0,
        help="inject transport-write failures on this fraction of "
        "operations (deterministic counter schedule; drives the "
        "FaultInjector so brownout runs are one command)",
    )
    p.add_argument(
        "--fault-delay-ms", type=float, default=0.0,
        help="inject this write-path delay (every operation) — latency "
        "brownout for limiter/timeout tuning",
    )
    p.add_argument(
        "--lame-duck-drill", action="store_true",
        help="drain-under-load in one command: flood the target, trigger "
        "its /quitquitquit a third of the way in, and report what the "
        "clients saw (a clean drain = zero connection-reset errors). "
        "TERMINATES the target server.",
    )
    p.add_argument(
        "--lame-duck-grace-s", type=float, default=0.0,
        help="grace window passed to /quitquitquit (0 = half the press "
        "duration)",
    )
    args = p.parse_args(argv)

    service, _, method = args.method.rpartition(".")
    if not service:
        p.error("--method must be service.method")
    if args.payload_file:
        with open(args.payload_file, "rb") as f:
            payload = f.read()
    else:
        payload = b"x" * args.payload_bytes

    if args.lame_duck_drill:
        counts = run_lame_duck_drill(
            args.server,
            service,
            method,
            payload,
            threads=args.threads,
            duration=args.duration,
            timeout_ms=args.timeout_ms,
            grace_s=args.lame_duck_grace_s,
        )
        print(
            f"ok={counts['ok']} logoff={counts['logoff']} "
            f"reset={counts['reset']} other={counts['other']} "
            f"drained_clean={counts['drained_clean']}"
        )
        return 0 if counts["drained_clean"] else 1

    if args.reactors > 0:
        if args.transport == "tpu":
            p.error("--reactors drives TCP native channels; it cannot "
                    "combine with --transport tpu")
        stats = run_reactor_press(
            args.server,
            service,
            method,
            payload,
            reactors=args.reactors,
            conns_per_reactor=args.conns_per_reactor,
            duration=args.duration,
            timeout_ms=args.timeout_ms,
            fault_rate=args.fault_rate,
            fault_delay_ms=args.fault_delay_ms,
            compress_type=(
                "" if args.compress_type == "none" else args.compress_type
            ),
            auth_token=args.auth_token,
            trace_sample_rate=args.trace_sample_rate,
        )
        if stats["reactor_conns"]:
            dist = " ".join(
                f"r{i}={n}" for i, n in sorted(stats["reactor_conns"].items())
            )
        else:  # no portal on the target: show the client-side pins
            dist = "client-shards=" + ",".join(
                str(s) for s in stats["client_shards"]
            )
        print(f"per-reactor conns: {dist}", file=sys.stderr)
        if stats["cid_misroutes"]:
            print(
                f"cid misroutes observed: {stats['cid_misroutes']}",
                file=sys.stderr,
            )
        print(
            f"qps={stats['qps']:.0f} ok={stats['ok']} fail={stats['fail']} "
            f"avg={stats['latency_us_avg']:.0f}us "
            f"p50={stats['latency_us_p50']:.0f}us "
            f"p99={stats['latency_us_p99']:.0f}us "
            f"max={stats['latency_us_max']:.0f}us"
        )
        if args.fault_rate > 0 or args.fault_delay_ms > 0:
            return 0  # failures are the point of a brownout run
        return 0 if stats["fail"] == 0 else 1

    stats = run_press(
        args.server,
        service,
        method,
        payload,
        threads=args.threads,
        duration=args.duration,
        timeout_ms=args.timeout_ms,
        transport=args.transport,
        native_plane=args.native_plane,
        fault_rate=args.fault_rate,
        fault_delay_ms=args.fault_delay_ms,
        compress_type=(
            "" if args.compress_type == "none" else args.compress_type
        ),
        auth_token=args.auth_token,
        trace_sample_rate=args.trace_sample_rate,
    )
    print(
        f"qps={stats['qps']:.0f} ok={stats['ok']} fail={stats['fail']} "
        f"avg={stats['latency_us_avg']:.0f}us p50={stats['latency_us_p50']:.0f}us "
        f"p99={stats['latency_us_p99']:.0f}us max={stats['latency_us_max']:.0f}us"
    )
    if args.fault_rate > 0 or args.fault_delay_ms > 0:
        return 0  # failures are the point of a brownout run
    return 0 if stats["fail"] == 0 else 1


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
