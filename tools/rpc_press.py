#!/usr/bin/env python
"""rpc_press — load generator for tbus_std servers (reference
tools/rpc_press: drives a method at a target qps/concurrency and reports
qps + latency percentiles).

Usage:
    python tools/rpc_press.py --server 127.0.0.1:8000 \
        --method demo.echo --payload-bytes 64 --threads 8 --duration 5
"""

from __future__ import annotations

import argparse
import sys
import threading
import time


def run_press(
    server: str,
    service: str,
    method: str,
    payload: bytes,
    threads: int = 4,
    duration: float = 5.0,
    timeout_ms: float = 1000,
    transport: str = "tcp",
    native_plane: bool = False,
    fault_rate: float = 0.0,
    fault_delay_ms: float = 0.0,
) -> dict:
    from incubator_brpc_tpu.bvar import LatencyRecorder
    from incubator_brpc_tpu.rpc import Channel, ChannelOptions

    if fault_rate > 0 or fault_delay_ms > 0:
        # one-command brownout run: arm the deterministic FaultInjector at
        # this process's socket-write seam (rpc/fault_injector.py) so a
        # scripted fraction of the press traffic fails/stalls — what the
        # limiter/breaker/retry machinery is tuned against
        from incubator_brpc_tpu.rpc import FaultInjector, install_socket_injector
        from incubator_brpc_tpu.utils.flags import set_flag_unchecked

        if native_plane:
            # the injector lives at the Python Socket.write seam; the C++
            # client channel never crosses it — a "brownout" that injects
            # nothing would be silently misleading
            print(
                "fault injection forces the Python plane "
                "(--native-plane ignored for this run)",
                file=sys.stderr,
            )
            native_plane = False

        set_flag_unchecked("fault_injection", True)
        install_socket_injector(
            FaultInjector(
                error_rate=fault_rate,
                delay_rate=1.0 if fault_delay_ms > 0 else 0.0,
                delay_ms=fault_delay_ms,
            )
        )

    ch = Channel()
    if not ch.init(
        server,
        options=ChannelOptions(
            timeout_ms=timeout_ms, transport=transport, native_plane=native_plane
        ),
    ):
        raise SystemExit(f"cannot init channel to {server}")

    latency = LatencyRecorder(name=None)
    stop_at = time.monotonic() + duration
    counts = {"ok": 0, "fail": 0}
    lock = threading.Lock()

    def worker():
        ok = fail = 0
        while time.monotonic() < stop_at:
            t0 = time.perf_counter()
            cntl = ch.call_method(service, method, payload)
            if cntl.ok():
                ok += 1
                latency << (time.perf_counter() - t0) * 1e6
            else:
                fail += 1
        with lock:
            counts["ok"] += ok
            counts["fail"] += fail

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.monotonic() - t0
    return {
        "qps": counts["ok"] / wall if wall else 0.0,
        "ok": counts["ok"],
        "fail": counts["fail"],
        "latency_us_avg": latency.latency(),
        "latency_us_p50": latency.latency_percentile(0.5),
        "latency_us_p99": latency.latency_percentile(0.99),
        "latency_us_max": latency.max_latency(),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--server", required=True, help="ip:port or naming url")
    p.add_argument("--method", required=True, help="service.method")
    p.add_argument("--payload-bytes", type=int, default=64)
    p.add_argument("--payload-file", help="read request payload from a file")
    p.add_argument("--threads", type=int, default=4)
    p.add_argument("--duration", type=float, default=5.0, help="seconds")
    p.add_argument("--timeout-ms", type=float, default=1000)
    p.add_argument(
        "--transport", choices=("tcp", "tpu"), default="tcp",
        help="tpu = drive the load over device links (the rdma_performance "
        "client's use_rdma flag)",
    )
    p.add_argument(
        "--native-plane", action="store_true",
        help="route eligible calls through the C++ client channel",
    )
    p.add_argument(
        "--fault-rate", type=float, default=0.0,
        help="inject transport-write failures on this fraction of "
        "operations (deterministic counter schedule; drives the "
        "FaultInjector so brownout runs are one command)",
    )
    p.add_argument(
        "--fault-delay-ms", type=float, default=0.0,
        help="inject this write-path delay (every operation) — latency "
        "brownout for limiter/timeout tuning",
    )
    args = p.parse_args(argv)

    service, _, method = args.method.rpartition(".")
    if not service:
        p.error("--method must be service.method")
    if args.payload_file:
        with open(args.payload_file, "rb") as f:
            payload = f.read()
    else:
        payload = b"x" * args.payload_bytes

    stats = run_press(
        args.server,
        service,
        method,
        payload,
        threads=args.threads,
        duration=args.duration,
        timeout_ms=args.timeout_ms,
        transport=args.transport,
        native_plane=args.native_plane,
        fault_rate=args.fault_rate,
        fault_delay_ms=args.fault_delay_ms,
    )
    print(
        f"qps={stats['qps']:.0f} ok={stats['ok']} fail={stats['fail']} "
        f"avg={stats['latency_us_avg']:.0f}us p50={stats['latency_us_p50']:.0f}us "
        f"p99={stats['latency_us_p99']:.0f}us max={stats['latency_us_max']:.0f}us"
    )
    if args.fault_rate > 0 or args.fault_delay_ms > 0:
        return 0  # failures are the point of a brownout run
    return 0 if stats["fail"] == 0 else 1


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
