#!/usr/bin/env python
"""parallel_http — fetch many URLs concurrently through the HTTP channel
client (reference tools/parallel_http/parallel_http.cpp: "access many
http servers in parallel, much faster than curl called in batch").

Usage:
    python tools/parallel_http.py --url-file urls.txt --threads 8
    echo http://127.0.0.1:8000/health | python tools/parallel_http.py

Each output line: ``<status-or-error> <bytes> <ms> <url>``.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from urllib.parse import urlsplit


def _parse_url(url: str):
    """(host, port, path) from an http:// url."""
    parts = urlsplit(url if "//" in url else f"http://{url}")
    if parts.scheme not in ("http", ""):
        raise ValueError(f"only http urls supported: {url}")
    host = parts.hostname or ""
    port = parts.port or 80
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    return host, port, path


def fetch_all(
    urls, threads: int = 8, timeout_ms: float = 1000, max_retry: int = 3
):
    """Fetch every url over shared per-endpoint channels; returns results
    in input order: (url, status_or_None, body_len, elapsed_ms, error)."""
    from incubator_brpc_tpu.rpc import Channel, ChannelOptions

    channels = {}
    chan_lock = threading.Lock()

    def channel_for(host: str, port: int):
        key = (host, port)
        with chan_lock:
            ch = channels.get(key)
            if ch is None:
                ch = Channel()
                ok = ch.init(
                    f"{host}:{port}",
                    options=ChannelOptions(
                        protocol="http",
                        timeout_ms=timeout_ms,
                        max_retry=max_retry,
                    ),
                )
                channels[key] = ch if ok else None
            return channels[key]

    results = [None] * len(urls)
    cursor = [0]
    cursor_lock = threading.Lock()

    def worker():
        while True:
            with cursor_lock:
                i = cursor[0]
                if i >= len(urls):
                    return
                cursor[0] += 1
            url = urls[i]
            t0 = time.monotonic()
            try:
                from incubator_brpc_tpu.rpc import Controller

                host, port, path = _parse_url(url)
                ch = channel_for(host, port)
                if ch is None:
                    raise ConnectionError("channel init failed")
                cntl = Controller(timeout_ms=timeout_ms)
                cntl.request_extra = {
                    "http_path": path, "http_method": "GET"
                }
                cntl = ch.call_method("", "", b"", cntl=cntl)
                ms = (time.monotonic() - t0) * 1e3
                if cntl.ok():
                    results[i] = (
                        url, cntl.http_status,
                        len(cntl.response_payload), ms, "",
                    )
                else:
                    results[i] = (
                        url, getattr(cntl, "http_status", None), 0, ms,
                        cntl.error_text,
                    )
            except (OSError, ValueError, ConnectionError) as e:
                ms = (time.monotonic() - t0) * 1e3
                results[i] = (url, None, 0, ms, str(e))

    ts = [threading.Thread(target=worker) for _ in range(max(1, threads))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url-file", default="", help="file of urls; default stdin")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--timeout-ms", type=float, default=1000)
    ap.add_argument("--max-retry", type=int, default=3)
    args = ap.parse_args()
    if args.url_file:
        with open(args.url_file) as f:
            urls = [ln.strip() for ln in f if ln.strip()]
    else:
        urls = [ln.strip() for ln in sys.stdin if ln.strip()]
    if not urls:
        print("no urls", file=sys.stderr)
        return 1
    t0 = time.monotonic()
    results = fetch_all(
        urls, threads=args.threads, timeout_ms=args.timeout_ms,
        max_retry=args.max_retry,
    )
    nok = 0
    for url, status, nbytes, ms, err in results:
        if err:
            print(f"ERR({err[:40]}) {nbytes} {ms:.1f} {url}")
        else:
            nok += 1
            print(f"{status} {nbytes} {ms:.1f} {url}")
    dt = time.monotonic() - t0
    print(
        f"# {nok}/{len(urls)} ok in {dt*1e3:.0f} ms "
        f"({len(urls)/max(dt,1e-9):.0f} urls/s)",
        file=sys.stderr,
    )
    return 0 if nok == len(urls) else 2


if __name__ == "__main__":
    sys.exit(main())
