"""Lifecycle-balance lint — borrow/give_back, schedule/unschedule,
register/remove.

Three resource disciplines in this codebase are acquire/release pairs
living in *different* functions, which no test of either function alone
can check — and both shipped leak classes came from exactly this shape
(the PR 3 ``on_revived`` closure leak: LB stop never removed its hooks
from process-global sockets; the PR 1 scrape-vs-stop UAF: a drain hook
outliving its plane).  This pass makes the balance structural:

- ``lifecycle-borrow`` — every ``SimpleDataPool.borrow()`` result must
  either reach ``give_back`` in the same function, or be *stored* (an
  attribute, a context dict key) such that some function in the module
  that calls ``give_back`` mentions the storage key — the teardown path
  provably reaches the borrow.  An ownership transfer the analyzer
  cannot see carries ``# fabriclint: allow(lifecycle-borrow) <who owns
  it and where it dies>``.
- ``lifecycle-timer`` — every ``TimerThread.schedule(...)`` id must be
  stored, and the storage key must be mentioned by a function in the
  module that calls ``unschedule`` (the owner's stop/close path).  A
  *discarded* id can never be canceled: the armed timer pins its
  closure (and everything the closure captures — a whole LB, a whole
  server) until it fires, and fires into torn-down state.
  Self-terminating reschedule chains (health-check probes, drain
  watchers) are the legitimate exception — annotated, with the
  termination condition as the reason.
- ``lifecycle-callback`` — every hook registration
  (``sock.on_failed.append``/``on_revived.append``, naming
  ``add_observer``, prometheus ``register_scrape_hook``) must have a
  matching removal form in the same module (``.remove`` on the same
  hook, ``remove_observer``, ``unregister_scrape_hook``).  Hooks whose
  lifetime is the *hooked object's own* (the socket dies, the hook dies
  with it, and the closure pins nothing beyond the socket) are
  annotated with that ownership argument as the reason.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tools.fabricverify import Violation, allowed, scan_annotations
from tools.fabricverify.lockorder import _attr_chain, iter_pkg_files

# hook list attributes whose .append is a registration needing a .remove
_HOOK_ATTRS = ("on_failed", "on_revived")
# paired registration/removal call names (by function/method name)
_PAIRED_CALLS = {
    "add_observer": "remove_observer",
    "register_scrape_hook": "unregister_scrape_hook",
}


def _mentions(fn: ast.AST) -> Set[str]:
    """Every identifier-ish token a function mentions: Name ids,
    attribute names, and string constants — the key universe the balance
    matcher searches."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
    return out


def _enclosing_functions(tree: ast.Module) -> List[ast.AST]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def _own_walk(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested defs — every
    call is attributed to its innermost function exactly once."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def _is_timer_schedule(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Attribute) and node.func.attr == "schedule"):
        return False
    chain = _attr_chain(node.func)
    return any("timer" in part.lower() for part in chain[:-1])


@dataclass
class _ModuleScan:
    path: str
    tree: Optional[ast.Module] = None
    # functions that call unschedule / give_back, with their mention sets
    unschedule_mentions: List[Set[str]] = field(default_factory=list)
    give_back_mentions: List[Set[str]] = field(default_factory=list)
    removal_hooks: Set[str] = field(default_factory=set)  # on_failed/on_revived
    removal_calls: Set[str] = field(default_factory=set)  # remove_observer etc.


def _scan_module(path: str, source: str) -> Optional[_ModuleScan]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    scan = _ModuleScan(path=path, tree=tree)
    for fn in _enclosing_functions(tree):
        calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
        names = {_call_name(c) for c in calls}
        if "unschedule" in names:
            scan.unschedule_mentions.append(_mentions(fn))
        if "give_back" in names:
            scan.give_back_mentions.append(_mentions(fn))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in _PAIRED_CALLS.values():
            scan.removal_calls.add(name)
        if name == "remove" and isinstance(node.func, ast.Attribute):
            chain = _attr_chain(node.func)
            if len(chain) >= 2 and chain[-2] in _HOOK_ATTRS:
                scan.removal_hooks.add(chain[-2])
    return scan


def _storage_keys_of(var: str, fn: ast.AST) -> Set[str]:
    """Where a local ``var`` (a borrowed object / timer id) is stored:
    attribute names it is assigned to, subscript string keys, and the
    receiving list attr of ``X.append(var)``."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            is_var = isinstance(node.value, ast.Name) and node.value.id == var
            if not is_var:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    keys.add(tgt.attr)
                elif isinstance(tgt, ast.Subscript):
                    sl = tgt.slice
                    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                        keys.add(sl.value)
                    else:
                        # keyed container (``self._revive_timers[ep] = tid``):
                        # the container attr is the storage key
                        base = _attr_chain(tgt.value)
                        if base:
                            keys.add(base[-1])
        elif isinstance(node, ast.Call):
            if (
                _call_name(node) == "append"
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == var
                and isinstance(node.func, ast.Attribute)
            ):
                chain = _attr_chain(node.func)
                if len(chain) >= 2:
                    keys.add(chain[-2])
    return keys


def _schedule_storage(node: ast.Call, parents: Dict[ast.AST, ast.AST]):
    """How a ``schedule(...)`` result is captured: ('attr'|'sub'|'append',
    key), ('local', name), or None when the id is discarded."""
    parent = parents.get(node)
    if isinstance(parent, ast.Assign):
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Attribute):
            return ("attr", tgt.attr)
        if isinstance(tgt, ast.Name):
            return ("local", tgt.id)
        if isinstance(tgt, ast.Subscript):
            sl = tgt.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return ("sub", sl.value)
            base = _attr_chain(tgt.value)
            if base:
                return ("attr", base[-1])
    if (
        isinstance(parent, ast.Call)
        and _call_name(parent) == "append"
        and isinstance(parent.func, ast.Attribute)
    ):
        chain = _attr_chain(parent.func)
        if len(chain) >= 2:
            return ("append", chain[-2])
    if isinstance(parent, ast.Return):
        # the id escapes to the caller — the caller owns the balance
        return ("return", "")
    return None


def check_source(path: str, source: str) -> List[Violation]:
    ann = scan_annotations(path, source)
    out: List[Violation] = list(ann.bad)
    scan = _scan_module(path, source)
    if scan is None or scan.tree is None:
        return out

    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(scan.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def timer_balanced(key: str) -> bool:
        return any(key in m for m in scan.unschedule_mentions)

    def borrow_balanced(keys: Set[str]) -> bool:
        return any(
            keys & m for m in scan.give_back_mentions
        )

    for fn in _enclosing_functions(scan.tree):
        fn_calls = [n for n in _own_walk(fn) if isinstance(n, ast.Call)]
        fn_call_names = {_call_name(c) for c in fn_calls}

        for node in fn_calls:
            # -- lifecycle-timer ------------------------------------------
            if _is_timer_schedule(node):
                line = node.lineno
                if allowed(ann, "lifecycle-timer", line):
                    continue
                storage = _schedule_storage(node, parents)
                if storage is None:
                    out.append(
                        Violation(
                            "lifecycle-timer", path, line,
                            "timer id from schedule() is discarded — the "
                            "armed timer can never be unscheduled and pins "
                            "its closure until it fires (store the id and "
                            "unschedule on the owner's stop path, or "
                            "allow(lifecycle-timer) with the chain's "
                            "termination condition as the reason)",
                        )
                    )
                    continue
                kind, key = storage
                if kind == "return":
                    continue
                if kind == "local":
                    # a local id is fine if unscheduled here, or if it is
                    # stored onward under a key the teardown path mentions
                    if "unschedule" in fn_call_names:
                        continue
                    onward = _storage_keys_of(key, fn)
                    if onward and any(timer_balanced(k) for k in onward):
                        continue
                    out.append(
                        Violation(
                            "lifecycle-timer", path, line,
                            f"timer id stored in local {key!r} with no "
                            "unschedule in the same function and no onward "
                            "storage a teardown path mentions — it dies "
                            "with the frame and the timer outlives it",
                        )
                    )
                    continue
                if not timer_balanced(key):
                    out.append(
                        Violation(
                            "lifecycle-timer", path, line,
                            f"timer id stored under {key!r} but no "
                            "unschedule-calling function in this module "
                            "mentions that key — the owner's stop/close "
                            "path cannot cancel this timer",
                        )
                    )
                continue

            # -- lifecycle-borrow -----------------------------------------
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "borrow"
                and not node.args
            ):
                line = node.lineno
                if allowed(ann, "lifecycle-borrow", line):
                    continue
                parent = parents.get(node)
                var = None
                if isinstance(parent, ast.Assign) and isinstance(
                    parent.targets[0], ast.Name
                ):
                    var = parent.targets[0].id
                if var is None:
                    # borrowed object not even captured — unreturnable
                    out.append(
                        Violation(
                            "lifecycle-borrow", path, line,
                            "borrow() result is not captured — the object "
                            "can never reach give_back",
                        )
                    )
                    continue
                if "give_back" in fn_call_names:
                    continue  # balanced locally (try/finally or linear)
                keys = _storage_keys_of(var, fn)
                if keys and borrow_balanced(keys):
                    continue
                out.append(
                    Violation(
                        "lifecycle-borrow", path, line,
                        f"borrowed object {var!r} neither reaches give_back "
                        "in this function nor is stored under a key any "
                        "give_back-calling function in this module mentions"
                        " — the pool leaks one object per call "
                        "(allow(lifecycle-borrow) for a true ownership "
                        "transfer, naming the owner)",
                    )
                )
                continue

            # -- lifecycle-callback ---------------------------------------
            name = _call_name(node)
            if name in _PAIRED_CALLS:
                line = node.lineno
                if allowed(ann, "lifecycle-callback", line):
                    continue
                removal = _PAIRED_CALLS[name]
                if removal not in scan.removal_calls:
                    out.append(
                        Violation(
                            "lifecycle-callback", path, line,
                            f"{name}() here has no {removal}() anywhere in "
                            "this module — the registered object outlives "
                            "its owner (the registration pins it until the "
                            "registry dies)",
                        )
                    )
                continue
            if (
                name == "append"
                and isinstance(node.func, ast.Attribute)
            ):
                chain = _attr_chain(node.func)
                if len(chain) >= 2 and chain[-2] in _HOOK_ATTRS:
                    hook = chain[-2]
                    line = node.lineno
                    if allowed(ann, "lifecycle-callback", line):
                        continue
                    if hook not in scan.removal_hooks:
                        out.append(
                            Violation(
                                "lifecycle-callback", path, line,
                                f"{hook}.append() here has no "
                                f"{hook}.remove() anywhere in this module — "
                                "the hook (and everything its closure "
                                "captures) lives as long as the hooked "
                                "object (allow(lifecycle-callback) when "
                                "that IS the intended lifetime, saying why)",
                            )
                        )
    return out


def check(paths: Optional[List[str]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in paths if paths is not None else iter_pkg_files():
        with open(path, "r") as fh:
            source = fh.read()
        out.extend(check_source(path, source))
    return out
