"""Lock-order analyzer — a global lock-ordering graph for the package.

~40 classes across ``lb/``, ``runtime/``, ``bvar/``, ``transport/`` and
``builtin/`` guard state with ``threading.Lock``/``RLock``/``Condition``,
and nothing enforced an acquisition order between them: a PR that takes
lock B under lock A in one file and A under B in another compiles, passes
every single-threaded test, and deadlocks in production.  This pass makes
the order a checked artifact:

1. **Lock entities.**  Every lock *construction* site in the package is
   bound to a named entity: ``self._x = threading.Lock()`` inside class
   ``C`` becomes ``module.C._x`` (one entity per class attribute — all
   instances share the ordering discipline), module globals become
   ``module._name``, function locals ``module.func.<name>``.
   ``threading.Condition(self._lock)`` aliases the wrapped lock's entity
   (waiting on the condition IS holding that lock).  A construction the
   analyzer cannot bind is itself a violation (``lock-unmodeled``) —
   the coverage contract is *every* site, allowlist-free.
2. **Acquisitions.**  ``with <lock>:`` scopes, ``.acquire()`` /
   ``.release()`` pairs.  Lock expressions resolve through the enclosing
   class (``self._lock``, including same-module bases), module globals,
   tracked local assignments, then a repo-unique attribute-name match
   (``sock._wlock`` → ``Socket._wlock``); an attribute name owned by
   several classes becomes one conservative *family* entity (``*._lock``).
3. **Edges.**  Acquiring B while holding A adds edge A→B.  An
   intraprocedural call graph (self-calls, module functions, imported
   names, repo-unique method names minus a builtin-shadowing blacklist)
   propagates callee lock effects: calling ``f`` while holding A adds
   A→every lock ``f`` may (transitively) acquire.  ``with`` over a call
   (``with self._dbd.read():``) holds the callee's effects for the body.
4. **Verdict.**  Cycles (incl. self-loops — re-acquiring a
   non-reentrant entity through a call chain) are ``lock-cycle``
   violations.  The acyclic graph is rendered as the documented lock
   hierarchy in docs/ANALYSIS.md (``--write-docs`` regenerates; a tier-1
   test keeps the doc in sync with the tree).

Exemptions: ``# fabriclint: allow(lock-cycle) <reason>`` on an
acquisition line removes the edges that line contributes (annotate the
acquisition that intentionally inverts, with the protocol that makes it
safe as the reason); ``allow(lock-unmodeled)`` on a construction site.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.fabricverify import (
    REPO_ROOT,
    Violation,
    allowed,
    scan_annotations,
)

PKG = "incubator_brpc_tpu"
PKG_ROOT = os.path.join(REPO_ROOT, PKG)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

# Method/function names never resolved by the unique-name fallback: they
# shadow list/dict/set/str/file/socket/threading protocol names, so an
# attribute call through an *unindexed* receiver (a file object, a deque)
# would be mis-bound to whatever package class happens to define the name.
_RESOLVE_BLACKLIST = {
    "get", "pop", "append", "appendleft", "popleft", "remove", "add",
    "discard", "clear", "update", "copy", "extend", "insert", "index",
    "count", "sort", "reverse", "join", "split", "strip", "encode",
    "decode", "format", "items", "keys", "values", "setdefault",
    "read", "write", "close", "flush", "tell", "seek", "readline",
    "send", "recv", "sendall", "connect", "bind", "listen", "accept",
    "set", "is_set", "wait", "wait_for", "notify", "notify_all",
    "acquire", "release", "locked", "start", "run", "stop", "put",
    "empty", "full", "qsize", "cancel", "result", "done", "shutdown",
    "fileno", "load", "store", "exchange", "search", "match", "group",
}

# local variable names treated as locks when nothing else resolves them
# (the `for lk in wrappers: lk.acquire()` / `lock = ...` idioms)
_LOCKISH_HINTS = ("lock", "mutex", "cond", "sem")


def _is_lockish_name(name: str) -> bool:
    low = name.lower()
    return low in ("lk", "lck") or any(h in low for h in _LOCKISH_HINTS)


@dataclass
class LockEntity:
    key: str                 # canonical id, e.g. "transport/sock.Socket._wlock"
    kind: str                # class-attr | module-global | local | dict-key | family | site
    path: str = ""
    line: int = 0
    alias_of: Optional[str] = None   # Condition(some_lock) wraps that entity

    def __hash__(self):  # entities are interned by key
        return hash(self.key)


@dataclass
class _ClassInfo:
    name: str
    bases: List[str]
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> entity key
    # attr -> class name it is an instance of (``self._dbd =
    # DoublyBufferedData(...)`` / the AnnAssign annotation)
    attr_instances: Dict[str, str] = field(default_factory=dict)


@dataclass
class _ModuleIndex:
    path: str
    rel: str                  # repo-relative, no .py — "transport/sock"
    tree: ast.Module = None
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    functions: Dict[str, ast.AST] = field(default_factory=dict)  # qualname -> node
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> module/name
    sites: List[Tuple[int, str]] = field(default_factory=list)  # (line, entity key)
    unmodeled: List[int] = field(default_factory=list)
    # module-level singletons: name -> ctor name (``span_store = SpanStore()``)
    instance_raw: Dict[str, str] = field(default_factory=dict)
    # lock attrs set on objects other than self (``server._hub_lock = Lock()``)
    foreign_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> key


@dataclass
class Analysis:
    entities: Dict[str, LockEntity] = field(default_factory=dict)
    # (holder, acquired) -> (path, line) of one witnessing acquisition
    edges: Dict[Tuple[str, str], Tuple[str, int]] = field(default_factory=dict)
    modules: Dict[str, _ModuleIndex] = field(default_factory=dict)
    violations: List[Violation] = field(default_factory=list)
    # function id -> transitive set of entity keys it may acquire
    effects: Dict[Tuple[str, str], Set[str]] = field(default_factory=dict)

    def site_count(self) -> int:
        return sum(len(m.sites) + len(m.unmodeled) for m in self.modules.values())


def _rel_of(path: str) -> str:
    rel = os.path.relpath(path, REPO_ROOT)
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.startswith(PKG + "/"):  # entity keys read better unprefixed
        rel = rel[len(PKG) + 1:]
    return rel


def _canon(entity: Dict[str, LockEntity], key: str) -> str:
    """Follow Condition→lock aliases to the canonical entity key."""
    seen = set()
    while key in entity and entity[key].alias_of and key not in seen:
        seen.add(key)
        key = entity[key].alias_of
    return key


def iter_pkg_files() -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(PKG_ROOT):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return out


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    if isinstance(node, ast.Call):
        # call-on-call chains: global_timer_thread().schedule — keep the
        # trailing attrs, mark the base as a call
        inner = _attr_chain(node.func)
        if inner:
            parts.append("()" + inner[-1])
            parts.reverse()
            return parts
    return []


def _is_lock_ctor(node: ast.Call, idx: _ModuleIndex) -> Optional[str]:
    """Return the ctor name if this Call constructs a threading primitive."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        if fn.value.id == "threading" and fn.attr in _LOCK_CTORS:
            return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        # only when imported from threading (``from threading import Lock``)
        if idx.imports.get(fn.id, "").startswith("threading."):
            return fn.id
    return None


# ---------------------------------------------------------------------------
# pass A: index modules — classes, functions, lock construction sites
# ---------------------------------------------------------------------------


def _index_module(path: str, source: str, entities: Dict[str, LockEntity]):
    idx = _ModuleIndex(path=path, rel=_rel_of(path))
    try:
        idx.tree = ast.parse(source)
    except SyntaxError:
        return idx
    for node in ast.walk(idx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                idx.imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                idx.imports[a.asname or a.name] = f"{node.module}.{a.name}"

    def index_func(fn: ast.AST, qual: str) -> None:
        idx.functions[qual] = fn
        for st in ast.walk(fn):
            if st is fn:
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if idx.functions.get(f"{qual}.{st.name}") is None:
                    index_func(st, f"{qual}.{st.name}")

    for node in idx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index_func(node, node.name)
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(
                name=node.name,
                bases=[b.id for b in node.bases if isinstance(b, ast.Name)],
            )
            idx.classes[node.name] = ci
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = sub
                    index_func(sub, f"{node.name}.{sub.name}")
        elif isinstance(node, ast.Assign):
            # module-level singleton: ``span_store = SpanStore()``
            if (
                len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
            ):
                idx.instance_raw[node.targets[0].id] = node.value.func.id

    # self-attr instances: ``self._dbd = DoublyBufferedData(...)`` (or the
    # AnnAssign annotation) — lets ``self._dbd.read()`` resolve precisely
    for ci in idx.classes.values():
        for m in ci.methods.values():
            for st in ast.walk(m):
                tgt = val = None
                if isinstance(st, ast.Assign) and len(st.targets) == 1:
                    tgt, val = st.targets[0], st.value
                elif isinstance(st, ast.AnnAssign):
                    tgt, val = st.target, st.value
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                cname = None
                if isinstance(st, ast.AnnAssign):
                    a = st.annotation
                    if isinstance(a, ast.Subscript):
                        a = a.value
                    if isinstance(a, ast.Name):
                        cname = a.id
                if (
                    cname is None
                    and isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Name)
                ):
                    cname = val.func.id
                if cname is not None and tgt.attr not in ci.attr_instances:
                    ci.attr_instances[tgt.attr] = cname

    _bind_ctor_sites(idx, entities)
    return idx


def _bind_ctor_sites(idx: _ModuleIndex, entities: Dict[str, LockEntity]) -> None:
    """Bind every lock-primitive construction to a named entity."""
    if idx.tree is None:
        return
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(idx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def enclosing(node, kinds):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = parents.get(cur)
        return None

    def new_entity(key, kind, line, alias_of=None):
        if key not in entities:
            entities[key] = LockEntity(
                key=key, kind=kind, path=idx.path, line=line, alias_of=alias_of
            )
        return key

    deferred_aliases: List[Tuple[str, str, ast.Call]] = []

    for node in ast.walk(idx.tree):
        if not (isinstance(node, ast.Call) and _is_lock_ctor(node, idx)):
            continue
        line = node.lineno
        ctor = _is_lock_ctor(node, idx)
        assign = enclosing(node, (ast.Assign, ast.AnnAssign))
        func = enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        cls = enclosing(node, ast.ClassDef)
        key = None
        kind = "site"
        if assign is not None:
            targets = assign.targets if isinstance(assign, ast.Assign) else [assign.target]
            tgt = targets[0] if targets else None
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and cls is not None
            ):
                key = f"{idx.rel}.{cls.name}.{tgt.attr}"
                kind = "class-attr"
                idx.classes[cls.name].lock_attrs[tgt.attr] = key
            elif isinstance(tgt, ast.Attribute) and isinstance(
                tgt.value, ast.Name
            ):
                # lock pinned on a foreign object (``server._hub_lock = …``)
                key = f"{idx.rel}.<{tgt.value.id}>.{tgt.attr}"
                kind = "foreign-attr"
                idx.foreign_attrs[tgt.attr] = key
            elif isinstance(tgt, ast.Name) and func is None:
                key = f"{idx.rel}.{tgt.id}"
                kind = "module-global"
            elif isinstance(tgt, ast.Name) and func is not None:
                key = f"{idx.rel}.{func.name}.<{tgt.id}>"
                kind = "local"
            elif isinstance(tgt, ast.Subscript):
                base = _attr_chain(tgt.value)
                key = f"{idx.rel}.{'.'.join(base) or 'map'}[*]"
                kind = "dict-key"
        if key is None:
            # ctor as an argument — e.g. ctx.setdefault("_fifo_lock", Lock())
            call = enclosing(node, ast.Call)
            if (
                call is not None
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "setdefault"
                and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
            ):
                key = f"{idx.rel}[{call.args[0].value}]"
                kind = "dict-key"
        if key is None:
            idx.unmodeled.append(line)
            continue
        new_entity(key, kind, line)
        idx.sites.append((line, key))
        # Condition(self._lock) wraps an existing lock: same entity
        if ctor == "Condition" and node.args:
            arg = node.args[0]
            if (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"
                and cls is not None
            ):
                deferred_aliases.append((key, f"{idx.rel}.{cls.name}.{arg.attr}", node))

    for key, target, _node in deferred_aliases:
        if target in entities and target != key:
            entities[key].alias_of = target


# ---------------------------------------------------------------------------
# pass B: per-function summaries (acquisitions + calls, with held sets)
# ---------------------------------------------------------------------------


@dataclass
class _FuncSummary:
    fid: Tuple[str, str]              # (module rel, qualname)
    acquires: List[Tuple[str, int, Tuple[str, ...]]] = field(default_factory=list)
    calls: List[Tuple[Tuple[Tuple[str, str], ...], Tuple[str, ...], int]] = field(
        default_factory=list
    )
    # lock entities this function RETURNS (``def _key_lock(...): return lk``):
    # ``with f():`` then holds the returned lock, not f's transient internals
    returns: Set[str] = field(default_factory=set)
    is_gen: bool = False              # generators (@contextmanager) hold
    #                                   their internal locks across the yield


class _Resolver:
    """Cross-module name resolution tables."""

    def __init__(self, modules: Dict[str, _ModuleIndex], entities):
        self.modules = modules
        self.entities = entities
        # lock attr name -> [entity keys] across every class
        self.attr_map: Dict[str, List[str]] = {}
        # method name -> [(module rel, qualname)]
        self.method_map: Dict[str, List[Tuple[str, str]]] = {}
        # module function name -> [(module rel, qualname)]
        self.func_map: Dict[str, List[Tuple[str, str]]] = {}
        # class name -> [(module rel, _ClassInfo)]
        self.class_map: Dict[str, List[Tuple[str, _ClassInfo]]] = {}
        self.by_rel: Dict[str, _ModuleIndex] = {}
        for m in modules.values():
            self.by_rel[m.rel] = m
            for ci in m.classes.values():
                self.class_map.setdefault(ci.name, []).append((m.rel, ci))
                for attr, key in ci.lock_attrs.items():
                    self.attr_map.setdefault(attr, []).append(key)
                for name in ci.methods:
                    self.method_map.setdefault(name, []).append(
                        (m.rel, f"{ci.name}.{name}")
                    )
            for attr, key in m.foreign_attrs.items():
                self.attr_map.setdefault(attr, []).append(key)
            for qual in m.functions:
                if "." not in qual:
                    self.func_map.setdefault(qual, []).append((m.rel, qual))

    def _class_of(self, mod: _ModuleIndex, cname: str):
        """(module rel, _ClassInfo) for a class name seen in ``mod``."""
        if cname in mod.classes:
            return (mod.rel, mod.classes[cname])
        target = mod.imports.get(cname, "")
        if target.startswith(PKG + "."):
            last = target.rsplit(".", 1)[-1]
            for rel, ci in self.class_map.get(last, ()):
                return (rel, ci)
        cands = self.class_map.get(cname, ())
        if len(cands) == 1:
            return cands[0]
        return None

    def _instance_class(self, mod: _ModuleIndex, name: str):
        """Resolve a module-level singleton name to its class."""
        ctor = mod.instance_raw.get(name)
        if ctor is not None:
            return self._class_of(mod, ctor)
        target = mod.imports.get(name, "")
        if target.startswith(PKG + "."):
            # from pkg.mod import breaker_registry — chase the singleton
            # assignment in its home module
            mod_path, last = target.rsplit(".", 1)
            rel = mod_path[len(PKG) + 1:].replace(".", "/")
            home = self.by_rel.get(rel) or self.by_rel.get(f"{rel}/__init__")
            if home is not None and last in home.instance_raw:
                return self._class_of(home, home.instance_raw[last])
        return None

    def _method_on(self, owner, name: str):
        """[(module rel, qualname)] for method ``name`` on class ``owner``
        (searching same-module bases)."""
        rel, ci = owner
        mod = self.by_rel.get(rel)
        seen = set()
        while ci is not None and ci.name not in seen:
            seen.add(ci.name)
            if name in ci.methods:
                return [(rel, f"{ci.name}.{name}")]
            nxt = None
            if mod is not None:
                for b in ci.bases:
                    if b in mod.classes:
                        nxt = mod.classes[b]
                        break
            ci = nxt
        return []

    def family(self, attr: str) -> str:
        key = f"*.{attr}"
        if key not in self.entities:
            self.entities[key] = LockEntity(key=key, kind="family")
        return key

    def resolve_lock_attr(self, attr: str) -> Optional[str]:
        owners = self.attr_map.get(attr)
        if not owners:
            return None
        if len(set(owners)) == 1:
            return owners[0]
        return self.family(attr)

    def resolve_call(
        self, node: ast.Call, mod: _ModuleIndex, cls: Optional[_ClassInfo]
    ) -> List[Tuple[str, str]]:
        fn = node.func
        if isinstance(fn, ast.Name):
            name = fn.id
            # local class instantiation -> __init__
            if name in mod.classes and "__init__" in mod.classes[name].methods:
                return [(mod.rel, f"{name}.__init__")]
            if name in mod.functions and "." not in name:
                return [(mod.rel, name)]
            target = mod.imports.get(name, "")
            if target.startswith(PKG + "."):
                # from pkg.mod import f  -> resolve f in that module
                parts = target[len(PKG) + 1:].split(".")
                fname = parts[-1]
                cands = [
                    c for c in self.func_map.get(fname, ())
                ] + [c for c in self.method_map.get(fname, ())]
                if len(cands) == 1:
                    return cands
            # repo-unique module function by bare name (imports move around)
            cands = self.func_map.get(name, ())
            if len(cands) == 1:
                return list(cands)
            return []
        if isinstance(fn, ast.Attribute):
            name = fn.attr
            base = fn.value
            if isinstance(base, ast.Name) and base.id == "self" and cls is not None:
                got = self._method_on((mod.rel, cls), name)
                if got:
                    return got
                # typed self-attr? (``self.<a>.<m>()`` with one level)
            if isinstance(base, ast.Name) and base.id != "self":
                owner = self._instance_class(mod, base.id)
                if owner is not None:
                    got = self._method_on(owner, name)
                    if got:
                        return got
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and cls is not None
            ):
                # ``self._dbd.read()`` via the attr's recorded instance class
                # (searching same-module bases — _SnapshotLB owns _dbd)
                cname = None
                info, seen = cls, set()
                while info is not None and info.name not in seen:
                    seen.add(info.name)
                    cname = info.attr_instances.get(base.attr)
                    if cname is not None:
                        break
                    info = next(
                        (mod.classes[b] for b in info.bases if b in mod.classes),
                        None,
                    )
                if cname is not None:
                    owner = self._class_of(mod, cname)
                    if owner is not None:
                        got = self._method_on(owner, name)
                        if got:
                            return got
            if name in _RESOLVE_BLACKLIST:
                return []
            cands = list(self.method_map.get(name, ())) + list(
                self.func_map.get(name, ())
            )
            if len(cands) == 1:
                return cands
            return []
        return []


class _FuncVisitor:
    """Walk one function body tracking the held-lock stack."""

    def __init__(self, summary, mod, cls, resolver, ann):
        self.s = summary
        self.mod = mod
        self.cls = cls
        self.r = resolver
        self.ann = ann
        self.held: List[str] = []
        self.locals: Dict[str, str] = {}  # local var -> entity key

    # -- lock expression resolution ----------------------------------------

    def resolve_lock(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id in self.locals:
                return self.locals[expr.id]
            mg = f"{self.mod.rel}.{expr.id}"
            if mg in self.r.entities:
                return mg
            if _is_lockish_name(expr.id):
                return self._local_entity(expr.id)
            return None
        chain = _attr_chain(expr)
        if not chain:
            return None
        attr = chain[-1]
        if chain[0] == "self" and len(chain) == 2 and self.cls is not None:
            info = self.cls
            seen = set()
            while info is not None and info.name not in seen:
                seen.add(info.name)
                if attr in info.lock_attrs:
                    return info.lock_attrs[attr]
                info = next(
                    (
                        self.mod.classes[b]
                        for b in info.bases
                        if b in self.mod.classes
                    ),
                    None,
                )
            # self attr that is not a known lock of this class: fall through
        resolved = self.r.resolve_lock_attr(attr)
        if resolved is not None:
            return resolved
        if _is_lockish_name(attr):
            return self.r.family(attr)
        return None

    def _local_entity(self, name: str) -> str:
        key = f"{self.mod.rel}.{self.s.fid[1]}.<{name}>"
        if key not in self.r.entities:
            self.r.entities[key] = LockEntity(key=key, kind="local")
        self.locals[name] = key
        return key

    # -- events -------------------------------------------------------------

    def _acquire(self, key: str, line: int) -> None:
        key = _canon(self.r.entities, key)
        self.s.acquires.append((key, line, tuple(self.held)))
        self.held.append(key)

    def _release(self, key: str) -> None:
        key = _canon(self.r.entities, key)
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i] == key:
                del self.held[i]
                return

    def _record_call(self, node: ast.Call) -> None:
        cands = self.r.resolve_call(node, self.mod, self.cls)
        if cands and self.held:
            self.s.calls.append((tuple(cands), tuple(self.held), node.lineno))

    # -- the walk -----------------------------------------------------------

    def visit_body(self, stmts) -> None:
        for st in stmts:
            self.visit(st)

    def visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested defs analyzed as their own functions
        if isinstance(node, ast.With):
            self._visit_with(node)
            return
        if isinstance(node, ast.Assign):
            self._visit_assign(node)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node)
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            self.s.is_gen = True
        if isinstance(node, ast.Return) and node.value is not None:
            key = None
            if isinstance(node.value, (ast.Attribute, ast.Name)):
                key = self.resolve_lock_no_synth(node.value)
            if key is not None:
                self.s.returns.add(_canon(self.r.entities, key))
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _visit_assign(self, node: ast.Assign) -> None:
        # track `x = <lock expr>` so later `with x:` resolves
        self.visit(node.value)
        name_tgt = next(
            (t for t in node.targets if isinstance(t, ast.Name)), None
        )
        if name_tgt is not None:
            key = None
            if isinstance(node.value, (ast.Attribute, ast.Name)):
                key = self.resolve_lock_no_synth(node.value)
            elif isinstance(node.value, ast.Call) and _is_lock_ctor(
                node.value, self.mod
            ):
                key = self.resolve_lock(name_tgt)
            if key is not None:
                self.locals[name_tgt.id] = _canon(self.r.entities, key)

    def resolve_lock_no_synth(self, expr) -> Optional[str]:
        """Resolve without inventing local/family entities (assignment
        tracking must not turn every `x = self.foo` into a lock)."""
        if isinstance(expr, ast.Name):
            if expr.id in self.locals:
                return self.locals[expr.id]
            mg = f"{self.mod.rel}.{expr.id}"
            return mg if mg in self.r.entities else None
        chain = _attr_chain(expr)
        if not chain:
            return None
        attr = chain[-1]
        if chain[0] == "self" and len(chain) == 2 and self.cls is not None:
            if attr in self.cls.lock_attrs:
                return self.cls.lock_attrs[attr]
        owners = self.r.attr_map.get(attr)
        if owners and len(set(owners)) == 1:
            return owners[0]
        return None

    def _visit_with(self, node: ast.With) -> None:
        acquired: List[str] = []
        call_effect_holds: List[str] = []
        for item in node.items:
            ctx = item.context_expr
            key = None
            if isinstance(ctx, (ast.Attribute, ast.Name)):
                key = self.resolve_lock(ctx)
            if key is not None:
                # an allow(lock-cycle) on this line removes the edges the
                # acquisition would contribute (both directions)
                if not allowed(self.ann, "lock-cycle", node.lineno):
                    self._acquire(key, node.lineno)
                    acquired.append(key)
            elif isinstance(ctx, ast.Call):
                # `with self._dbd.read():` — hold the callee's lock effects
                # for the body (context-manager approximation) and record
                # the call itself
                self.visit(ctx)
                cands = self.r.resolve_call(ctx, self.mod, self.cls)
                if cands:
                    if self.held:
                        self.s.calls.append(
                            (tuple(cands), tuple(self.held), ctx.lineno)
                        )
                    marker = f"@cm:{ctx.lineno}:" + ",".join(
                        f"{m}:{q}" for m, q in cands
                    )
                    self.held.append(marker)
                    call_effect_holds.append(marker)
            else:
                self.visit(ctx)
        self.visit_body(node.body)
        for key in reversed(acquired):
            self._release(key)
        for marker in reversed(call_effect_holds):
            if marker in self.held:
                self.held.remove(marker)

    def _visit_call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "acquire" and len(chain) > 1:
            recv = node.func.value
            key = self.resolve_lock(recv) if isinstance(
                recv, (ast.Attribute, ast.Name)
            ) else None
            if key is not None:
                if not allowed(self.ann, "lock-cycle", node.lineno):
                    self._acquire(key, node.lineno)
                for a in node.args:
                    self.visit(a)
                return
        if chain and chain[-1] == "release" and len(chain) > 1:
            recv = node.func.value
            key = None
            if isinstance(recv, (ast.Attribute, ast.Name)):
                key = self.resolve_lock_no_synth(recv) or (
                    self.resolve_lock(recv)
                )
            if key is not None:
                self._release(key)
                return
        self._record_call(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)


# ---------------------------------------------------------------------------
# pass C: effect propagation + edge construction + cycles
# ---------------------------------------------------------------------------


def analyze(paths: Optional[List[str]] = None) -> Analysis:
    an = Analysis()
    files = paths if paths is not None else iter_pkg_files()
    sources: Dict[str, str] = {}
    for path in files:
        with open(path, "r") as fh:
            sources[path] = fh.read()
        an.modules[path] = _index_module(path, sources[path], an.entities)

    resolver = _Resolver(an.modules, an.entities)

    summaries: Dict[Tuple[str, str], _FuncSummary] = {}
    anns = {}
    for path, mod in an.modules.items():
        if mod.tree is None:
            continue
        ann = scan_annotations(path, sources[path])
        anns[path] = ann
        for qual, fn in mod.functions.items():
            cls = None
            if "." in qual:
                cname = qual.split(".")[0]
                cls = mod.classes.get(cname)
            s = _FuncSummary(fid=(mod.rel, qual))
            v = _FuncVisitor(s, mod, cls, resolver, ann)
            v.visit_body(fn.body)
            summaries[s.fid] = s

    # transitive lock effects per function (fixed point)
    effects: Dict[Tuple[str, str], Set[str]] = {
        fid: {a for a, _l, _h in s.acquires} for fid, s in summaries.items()
    }
    changed = True
    while changed:
        changed = False
        for fid, s in summaries.items():
            cur = effects[fid]
            before = len(cur)
            for cands, _held, _line in s.calls:
                for c in cands:
                    cur |= effects.get(c, set())
            if len(cur) != before:
                changed = True
    an.effects = effects

    def expand_held(held: Tuple[str, ...]) -> List[str]:
        out: List[str] = []
        for h in held:
            if h.startswith("@cm:"):
                for part in h.split(":", 2)[2].split(","):
                    m, q = part.split(":", 1)
                    s = summaries.get((m, q))
                    if s is not None and s.returns and not s.is_gen:
                        # ``with f():`` over a lock-returning helper holds
                        # the RETURNED lock; f's internal acquisitions are
                        # transient (covered by the call edge at the call)
                        out.extend(s.returns)
                    else:
                        out.extend(effects.get((m, q), ()))
            else:
                out.append(h)
        return out

    def add_edge(a: str, b: str, path: str, line: int) -> None:
        if a == b and an.entities.get(a, LockEntity(a, "")).kind == "family":
            # two different locks sharing an ambiguous family name are not
            # evidence of re-acquisition — only precise self-loops count
            return
        an.edges.setdefault((a, b), (path, line))

    for fid, s in summaries.items():
        mod_path = next(
            p for p, m in an.modules.items() if m.rel == fid[0]
        )
        for acquired, line, held in s.acquires:
            for h in expand_held(held):
                add_edge(h, acquired, mod_path, line)
        for cands, held, line in s.calls:
            flat = expand_held(held)
            if not flat:
                continue
            callee_locks: Set[str] = set()
            for c in cands:
                callee_locks |= effects.get(c, set())
            for h in flat:
                for l in callee_locks:
                    add_edge(h, l, mod_path, line)

    # unmodeled construction sites
    for path, mod in an.modules.items():
        ann = anns.get(path)
        for line in mod.unmodeled:
            if ann is None or not allowed(ann, "lock-unmodeled", line):
                an.violations.append(
                    Violation(
                        "lock-unmodeled", path, line,
                        "lock primitive constructed here could not be bound "
                        "to a named entity — name it (assign to an attribute "
                        "or variable) or allow(lock-unmodeled) with a reason",
                    )
                )

    _find_cycles(an)
    return an


def _find_cycles(an: Analysis) -> None:
    graph: Dict[str, Set[str]] = {}
    for (a, b) in an.edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    # iterative Tarjan SCC
    index_counter = [0]
    stack: List[str] = []
    lowlink: Dict[str, int] = {}
    index: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    sccs: List[List[str]] = []

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph[root])))]
        index[root] = lowlink[root] = index_counter[0]
        index_counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = lowlink[succ] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(succ)
                    on_stack[succ] = True
                    work.append((succ, iter(sorted(graph[succ]))))
                    advanced = True
                    break
                elif on_stack.get(succ):
                    lowlink[node] = min(lowlink[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for comp in sccs:
        cyclic = len(comp) > 1 or (
            len(comp) == 1 and comp[0] in graph.get(comp[0], ())
        )
        if not cyclic:
            continue
        comp = sorted(comp)
        # witness: one edge inside the SCC, for file:line anchoring
        witness = None
        for (a, b), site in sorted(an.edges.items()):
            if a in comp and b in comp:
                witness = site
                break
        path, line = witness if witness else ("<graph>", 1)
        an.violations.append(
            Violation(
                "lock-cycle", path, line,
                "lock-ordering cycle: " + " -> ".join(comp + [comp[0]])
                + " (two code paths acquire these locks in opposite "
                "orders; break the cycle or allow(lock-cycle) the "
                "inverting acquisition with the protocol that makes it "
                "safe)",
            )
        )


# ---------------------------------------------------------------------------
# public surface
# ---------------------------------------------------------------------------


def check(paths: Optional[List[str]] = None) -> List[Violation]:
    an = analyze(paths)
    return list(an.violations)


DOC_BEGIN = "<!-- fabricverify:lock-hierarchy:begin -->"
DOC_END = "<!-- fabricverify:lock-hierarchy:end -->"


def render_hierarchy(an: Optional[Analysis] = None) -> str:
    """The acyclic lock-ordering graph as the documented hierarchy:
    topological levels (level 0 may be held while acquiring any deeper
    level; never the reverse), one line per ordered entity with its
    outgoing order edges, then the leaf locks that never nest."""

    if an is None:
        an = analyze()
    succ: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for (a, b) in an.edges:
        succ.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    indeg = {n: 0 for n in nodes}
    for a, bs in succ.items():
        for b in bs:
            indeg[b] += 1
    # Kahn levels (cycles, if any, are reported separately and excluded)
    levels: List[List[str]] = []
    remaining = dict(indeg)
    frontier = sorted(n for n, d in remaining.items() if d == 0)
    seen: Set[str] = set()
    while frontier:
        levels.append(frontier)
        seen |= set(frontier)
        nxt: Dict[str, int] = {}
        for n in frontier:
            for b in succ.get(n, ()):
                remaining[b] -= 1
        frontier = sorted(
            n for n, d in remaining.items() if d == 0 and n not in seen
        )
    lines = [
        "Generated by `python -m tools.fabricverify --write-docs` — do not",
        "edit by hand; a tier-1 test keeps this section in sync with the",
        "tree.  `A -> B` means some code path acquires B while holding A,",
        "so B must never be held while acquiring A.  Levels are a valid",
        "acquisition order: take locks strictly downward.",
        "",
        f"- lock construction sites modeled: **{an.site_count()}**",
        f"- lock entities: **{len(an.entities)}**"
        f" ({sum(1 for e in an.entities.values() if e.kind == 'family')}"
        " ambiguous families)",
        f"- order edges: **{len(an.edges)}**",
        "",
    ]
    for i, level in enumerate(levels):
        lines.append(f"**Level {i}**")
        lines.append("")
        for n in level:
            outs = sorted(succ.get(n, ()))
            if outs:
                lines.append(f"- `{n}` → " + ", ".join(f"`{o}`" for o in outs))
            else:
                lines.append(f"- `{n}`")
        lines.append("")
    solo = sorted(
        k for k, e in an.entities.items()
        if k not in nodes and e.alias_of is None and e.kind != "family"
    )
    lines.append(
        f"**Unordered** ({len(solo)} entities never nested with another "
        "lock; any order is safe today — an edge appearing here in a "
        "future run means new nesting was introduced):"
    )
    lines.append("")
    lines.append(", ".join(f"`{s}`" for s in solo))
    lines.append("")
    return "\n".join(lines)


def write_docs(doc_path: Optional[str] = None) -> bool:
    """Regenerate the lock-hierarchy section of docs/ANALYSIS.md between
    the begin/end markers. Returns True if the file changed."""

    if doc_path is None:
        doc_path = os.path.join(REPO_ROOT, "docs", "ANALYSIS.md")
    with open(doc_path, "r") as fh:
        text = fh.read()
    body = render_hierarchy()
    begin = text.index(DOC_BEGIN) + len(DOC_BEGIN)
    end = text.index(DOC_END)
    new = text[:begin] + "\n" + body + text[end:]
    if new != text:
        with open(doc_path, "w") as fh:
            fh.write(new)
        return True
    return False


def documented_hierarchy(doc_path: Optional[str] = None) -> str:
    """The committed hierarchy section (between the markers)."""
    if doc_path is None:
        doc_path = os.path.join(REPO_ROOT, "docs", "ANALYSIS.md")
    with open(doc_path, "r") as fh:
        text = fh.read()
    begin = text.index(DOC_BEGIN) + len(DOC_BEGIN)
    end = text.index(DOC_END)
    return text[begin:end].strip()
