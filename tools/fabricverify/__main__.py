"""CLI: ``python -m tools.fabricverify`` (half of the ``make lint``
entry point, merged with fabriclint's exit code).

Runs the lock-order, lifecycle, and model-checking passes and prints
violations one per line; exits 1 when any survive their annotations.

- ``--json``: machine-readable report — a JSON array of
  ``{rule, file, line, reason}`` records on stdout (the same schema as
  ``python -m tools.fabriclint --json``), so CI tooling can diff
  violation sets across commits.
- ``--rule <name>`` filters to one rule id; ``--list-rules`` prints the
  ids this tool owns.
- ``--write-docs`` regenerates the lock-hierarchy section of
  docs/ANALYSIS.md from the current tree and exits (0 = unchanged,
  2 = rewritten).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    from tools.fabricverify import RULES, run_all, to_records

    ap = argparse.ArgumentParser(prog="fabricverify")
    ap.add_argument("--rule", help="only report this rule id")
    ap.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit"
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit {rule, file, line, reason} records as a JSON array",
    )
    ap.add_argument(
        "--write-docs", action="store_true",
        help="regenerate the docs/ANALYSIS.md lock hierarchy and exit",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for r in RULES:
            print(r)
        return 0
    if args.write_docs:
        from tools.fabricverify import lockorder

        changed = lockorder.write_docs()
        print(
            "docs/ANALYSIS.md lock hierarchy "
            + ("rewritten" if changed else "already current"),
            file=sys.stderr,
        )
        return 2 if changed else 0
    violations = run_all()
    if args.rule:
        violations = [v for v in violations if v.rule == args.rule]
    if args.json:
        print(json.dumps(to_records(violations), indent=2))
        return 1 if violations else 0
    for v in violations:
        print(v)
    if violations:
        print(f"fabricverify: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("fabricverify: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
