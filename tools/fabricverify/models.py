"""Extracted protocol models for the explicit-state checker.

A model is a hand-extracted, exhaustively-explorable twin of a protocol
implemented in the package.  Extraction rules (see docs/ANALYSIS.md,
"writing a model for the checker"):

- State is a flat immutable tuple — every field that influences a
  branch in the real code, nothing that doesn't (payload bytes,
  latencies and ids are abstracted away; *counts and phases* stay).
- Every nondeterministic choice the real system faces (message
  delivery order, drops, duplicates, timer firings, party deaths) is an
  explicit ``actions()`` branch, so the explorer visits ALL
  interleavings that the bounded scope admits — the substitute for
  production soak.
- Known-bad variants are constructor flags (``drop_close_echo=True``),
  NOT separate models: the meta-tests instantiate the mutation and
  assert the checker flips red, proving the property actually binds.

Two models ship:

- :class:`SessionModel` — the mc_dispatch N-party session protocol
  (parallel/mc_dispatch.py): accept fan-out + barrier, the monotone
  ``final = max(proposed, all targets)`` join, run fan-out into the
  LOCKSTEP BARRIER (a party that entered its chain is blocked until
  every party joins — the device collective), the convergent close
  barrier where every party echoes ``final``, and the fault plane: up
  to ``max_deaths`` parties may die at any instant; the proposer
  detects an outstanding dead party (the failed-RPC / socket feedback
  of the real code) and broadcasts ABORT so every survivor leaves the
  barrier — the abort-convergence property asserts no living party is
  ever left stuck in the barrier at the end.  The environment may
  reorder (inherent — delivery picks any in-flight message), drop
  (≤ ``max_drops``) and duplicate (≤ ``max_dups``) messages.  The
  proposer may time out ONLY when something was actually dropped — so
  a deadlock on a drop-free, death-free path is a protocol bug, not an
  abstracted timeout.
- :class:`BreakerModel` — the circuit-breaker state machine
  (rpc/circuit_breaker.py + the LB isolation dance in lb/__init__.py):
  closed → trip → isolated → (elapse | early socket revive) →
  half_open → (window successes → closed with duration reset) |
  (error → re-trip with doubled, capped duration).
"""

from __future__ import annotations

from typing import List, Tuple

# ---------------------------------------------------------------------------
# mc_dispatch session protocol
# ---------------------------------------------------------------------------

# party phases
#   RUNNING = inside the lockstep barrier (entered the jitted chain; in a
#   real multi-controller run the party is BLOCKED here until every other
#   party joins — or the abort plane unwedges it)
P_IDLE, P_ACCEPTED, P_RUNNING, P_RAN, P_ABORTED = 0, 1, 2, 3, 4
# proposer phases
PR_ACCEPT_WAIT, PR_RUN_WAIT, PR_DONE, PR_ABORTED = 0, 1, 2, 3

REJECT = -1  # run_resp payload for a below-floor run proposal


class SessionModel:
    """State = (proposer_phase, final, acks, echoes, parties, msgs,
    drops_used, dups_used, dead, deaths_used) — all tuples/ints,
    hashable.

    - ``acks``/``echoes``: tuples of per-party values (None until heard).
    - ``parties``: tuple of (phase, target_or_ran_steps).
    - ``msgs``: sorted tuple of in-flight (kind, party, value) triples —
      a multiset; delivery picks ANY element, which IS reorder.
      Delivery to a dead party consumes the message silently.
    - ``dead``: tuple of per-party death flags (the environment may kill
      up to ``max_deaths`` parties at any instant).

    Mutations (each one seeded bug the meta-tests prove the checker
    catches):

    - ``drop_close_echo``: parties that completed the collective never
      send the close-barrier echo — the real-code analog of a
      lost/forgotten ``run_resp``; the proposer waits forever on a
      drop-free path.
    - ``min_join``: the proposer folds accept targets with ``min``
      instead of ``max`` — a party with a higher floor gets a run
      proposal below what it accepted and rejects (the run-phase floor
      check mc_dispatch enforces), so a drop-free session aborts.
    - ``no_floor_reject``: with ``min_join``, parties also skip the
      floor check and silently run fewer steps than they accepted —
      the close barrier then sees non-convergent echoes.
    - ``drop_abort``: the proposer aborts (death detected, reject,
      timeout) but the ABORT BROADCAST is never sent — survivors stay
      wedged in the lockstep barrier forever; the abort-convergence
      check in ``terminal_ok`` flips red with the stuck party named.
    """

    name = "mc_dispatch_session"
    source = "incubator_brpc_tpu/parallel/mc_dispatch.py"

    M_ACCEPT_REQ, M_ACCEPT_ACK, M_RUN_REQ, M_RUN_RESP, M_ABORT = 0, 1, 2, 3, 4

    def __init__(
        self,
        n_parties: int = 3,
        steps: int = 2,
        floors: Tuple[int, ...] = (0, 1, 3),
        max_drops: int = 1,
        max_dups: int = 1,
        max_deaths: int = 0,
        drop_close_echo: bool = False,
        min_join: bool = False,
        no_floor_reject: bool = False,
        drop_abort: bool = False,
    ):
        assert len(floors) == n_parties
        self.n = n_parties
        self.steps = steps
        self.floors = floors
        self.max_drops = max_drops
        self.max_dups = max_dups
        self.max_deaths = max_deaths
        self.drop_close_echo = drop_close_echo
        self.min_join = min_join
        self.no_floor_reject = no_floor_reject
        self.drop_abort = drop_abort
        if max_deaths > 0:
            self.name = "mc_dispatch_session_party_death"

    def initial_state(self):
        msgs = tuple(
            sorted((self.M_ACCEPT_REQ, i, self.steps) for i in range(self.n))
        )
        return (
            PR_ACCEPT_WAIT,
            0,                                  # final (0 = not joined yet)
            (None,) * self.n,                   # accept acks
            (None,) * self.n,                   # close echoes
            ((P_IDLE, 0),) * self.n,
            msgs,
            0,                                  # drops used
            0,                                  # dups used
            (False,) * self.n,                  # dead flags
            0,                                  # deaths used
        )

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _without(msgs, m):
        out = list(msgs)
        out.remove(m)
        return tuple(out)

    @staticmethod
    def _with(msgs, *new):
        return tuple(sorted(msgs + tuple(new)))

    def _abort_msgs(self, dead):
        """The abort broadcast: one M_ABORT per living party (the real
        proposer skips parties it already observed dead).  The
        ``drop_abort`` mutation loses the whole broadcast."""
        if self.drop_abort:
            return ()
        return tuple(
            (self.M_ABORT, j, 0) for j in range(self.n) if not dead[j]
        )

    def is_terminal(self, s) -> bool:
        phase, _f, _a, _e, _p, msgs, _d, _du, _dead, _dt = s
        return phase in (PR_DONE, PR_ABORTED) and not msgs

    def actions(self, s) -> List[Tuple[str, tuple]]:
        (phase, final, acks, echoes, parties, msgs, drops, dups, dead,
         deaths) = s
        out: List[Tuple[str, tuple]] = []
        for m in sorted(set(msgs)):
            out.append((f"deliver{m}", self._deliver(s, m)))
            if m[0] == self.M_ABORT:
                # abort delivery is modeled RELIABLE: in the real code a
                # lost abort rpc is backstopped by each party's own
                # session deadline (every party unwedges itself); were
                # drops allowed here, that backstop would have to be
                # modeled too and the broadcast property would go
                # vacuous.  What this model verifies instead is the
                # sharper claim: every abort path SENDS an abort to
                # every survivor (the drop_abort mutation breaks it).
                continue
            if drops < self.max_drops:
                out.append(
                    (f"drop{m}",
                     (phase, final, acks, echoes, parties,
                      self._without(msgs, m), drops + 1, dups, dead, deaths))
                )
            if dups < self.max_dups:
                out.append(
                    (f"dup{m}",
                     (phase, final, acks, echoes, parties,
                      self._with(msgs, m), drops, dups + 1, dead, deaths))
                )
        # the environment kills a party at any instant
        if deaths < self.max_deaths:
            for j in range(self.n):
                if not dead[j]:
                    out.append(
                        (f"die{j}",
                         (phase, final, acks, echoes, parties, msgs, drops,
                          dups,
                          dead[:j] + (True,) + dead[j + 1:], deaths + 1))
                    )
        # the lockstep collective completes only when EVERY party joined
        # the barrier alive — then all emit their close echoes at once
        if all(p[0] == P_RUNNING for p in parties) and not any(dead):
            newp = tuple((P_RAN, p[1]) for p in parties)
            newm = msgs
            if not self.drop_close_echo:
                newm = self._with(
                    msgs,
                    *[(self.M_RUN_RESP, j, parties[j][1])
                      for j in range(self.n)],
                )
            out.append(
                ("collective_complete",
                 (phase, final, acks, echoes, newp, newm, drops, dups, dead,
                  deaths))
            )
        # death detection (the real code's failed-RPC / dying-socket
        # feedback): a dead party the proposer still waits on triggers
        # the fabric-wide abort — broadcast + local abort state
        if phase in (PR_ACCEPT_WAIT, PR_RUN_WAIT):
            waiting_on_dead = any(
                dead[j]
                and (acks[j] is None if phase == PR_ACCEPT_WAIT
                     else echoes[j] is None)
                for j in range(self.n)
            )
            if waiting_on_dead:
                out.append(
                    ("detect_death",
                     (PR_ABORTED, final, acks, echoes, parties,
                      self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                      dead, deaths))
                )
        # the proposer's deadline: enabled only when the environment
        # actually lost something — a drop-free path must make progress
        # through protocol actions alone.  A timeout abort broadcasts
        # too (the real session deadline does).
        if phase in (PR_ACCEPT_WAIT, PR_RUN_WAIT) and drops > 0:
            out.append(
                ("timeout",
                 (PR_ABORTED, final, acks, echoes, parties,
                  self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                  dead, deaths))
            )
        return out

    def _deliver(self, s, m) -> tuple:
        (phase, final, acks, echoes, parties, msgs, drops, dups, dead,
         deaths) = s
        msgs = self._without(msgs, m)
        kind, i, val = m
        same = (phase, final, acks, echoes, parties, msgs, drops, dups, dead,
                deaths)

        if kind == self.M_ABORT:
            # a survivor leaves whatever pre-completion phase it is in —
            # including the lockstep barrier, the whole point of the
            # broadcast; a party that already RAN keeps its result
            if dead[i]:
                return same
            pphase, val0 = parties[i]
            if pphase in (P_IDLE, P_ACCEPTED, P_RUNNING):
                parties = (
                    parties[:i] + ((P_ABORTED, val0),) + parties[i + 1:]
                )
            return (phase, final, acks, echoes, parties, msgs, drops, dups,
                    dead, deaths)

        if kind == self.M_ACCEPT_REQ:
            if dead[i]:
                return same  # delivered to a corpse: consumed, no ack
            # party admission: its ack may RAISE the target to its floor
            # (mc_dispatch_min_steps); duplicates re-ack idempotently
            target = max(val, self.floors[i])
            pphase, _ = parties[i]
            newp = parties
            if pphase == P_IDLE:
                newp = (
                    parties[:i] + ((P_ACCEPTED, target),) + parties[i + 1:]
                )
            elif pphase == P_ABORTED:
                return same  # aborted party re-joins nothing
            msgs = self._with(msgs, (self.M_ACCEPT_ACK, i, target))
            return (phase, final, acks, echoes, newp, msgs, drops, dups,
                    dead, deaths)

        if kind == self.M_ACCEPT_ACK:
            if phase != PR_ACCEPT_WAIT or acks[i] is not None:
                return same
            acks = acks[:i] + (val,) + acks[i + 1:]
            if all(a is not None for a in acks):
                # the N-party join: monotone max (the seeded min_join
                # mutation folds with min — non-monotone, violating what
                # parties accepted)
                fold = min if self.min_join else max
                final = fold(self.steps, *[a for a in acks])
                msgs = self._with(
                    msgs,
                    *[(self.M_RUN_REQ, j, final) for j in range(self.n)],
                )
                return (
                    PR_RUN_WAIT, final, acks, echoes, parties, msgs, drops,
                    dups, dead, deaths,
                )
            return (phase, final, acks, echoes, parties, msgs, drops, dups,
                    dead, deaths)

        if kind == self.M_RUN_REQ:
            if dead[i]:
                return same
            pphase, target = parties[i]
            if pphase == P_ACCEPTED:
                if val < self.floors[i] and not self.no_floor_reject:
                    # run proposal below this party's accepted floor:
                    # clean reject on the control stream
                    msgs = self._with(msgs, (self.M_RUN_RESP, i, REJECT))
                    return (
                        phase, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths,
                    )
                # the party enters its lockstep chain and BLOCKS in the
                # collective barrier until everyone joins (or abort)
                parties = (
                    parties[:i] + ((P_RUNNING, val),) + parties[i + 1:]
                )
                return (phase, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths)
            if pphase == P_RAN:
                # duplicate run proposal: idempotent re-echo of what ran
                if not self.drop_close_echo:
                    msgs = self._with(
                        msgs, (self.M_RUN_RESP, i, parties[i][1])
                    )
                return (phase, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths)
            # idle (run before accept cannot happen — the ack caused the
            # fan-out), running (duplicate), or aborted: ignored
            return same

        # M_RUN_RESP
        if phase != PR_RUN_WAIT or echoes[i] is not None:
            return same
        if val == REJECT:
            # a reject aborts the whole session — and the survivors
            # already in the barrier must be told (abort broadcast),
            # exactly like a death
            return (PR_ABORTED, final, acks, echoes, parties,
                    self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                    dead, deaths)
        echoes = echoes[:i] + (val,) + echoes[i + 1:]
        if all(e is not None for e in echoes):
            ok = all(e == final for e in echoes)
            if ok:
                return (PR_DONE, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths)
            # non-convergent close: abort, and unwedge everyone
            return (PR_ABORTED, final, acks, echoes, parties,
                    self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                    dead, deaths)
        return (phase, final, acks, echoes, parties, msgs, drops, dups,
                dead, deaths)

    # -- properties ----------------------------------------------------------

    def invariant(self, s) -> str:
        """Safety on every reachable state; '' when fine."""
        _ph, final, _a, _e, parties, _m, _d, _du, _dead, _dt = s
        for i, (pphase, val) in enumerate(parties):
            if pphase == P_RAN and val < self.floors[i]:
                return (
                    f"party {i} ran {val} steps, below its accepted floor "
                    f"{self.floors[i]} — the join was not monotone"
                )
        return ""

    def terminal_ok(self, s) -> str:
        """Checked on terminal states; '' when fine."""
        (phase, final, _a, echoes, parties, _m, drops, _du, dead,
         deaths) = s
        # abort convergence: however the session ended, no LIVING party
        # may be left inside the lockstep barrier — that is a real
        # process wedged on a device collective forever
        for i, (pphase, _v) in enumerate(parties):
            if pphase == P_RUNNING and not dead[i]:
                return (
                    f"party {i} is alive and still stuck in the lockstep "
                    "barrier at session end — the abort never reached it"
                )
        if phase == PR_DONE:
            expect = max(self.steps, *self.floors)
            if final != expect:
                return (
                    f"session closed with final={final}, but the monotone "
                    f"join of proposed={self.steps} and floors="
                    f"{self.floors} is {expect}"
                )
            for i, (pphase, ran) in enumerate(parties):
                if pphase != P_RAN or ran != final:
                    return (
                        f"close converged but party {i} state is "
                        f"{(pphase, ran)}, expected ran {final}"
                    )
        if drops == 0 and deaths == 0 and phase != PR_DONE:
            return (
                "drop-free, death-free path ended without a converged "
                f"close (proposer phase {phase}) — the protocol aborted "
                "or diverged with no environment fault to blame"
            )
        return ""


# ---------------------------------------------------------------------------
# circuit-breaker state machine
# ---------------------------------------------------------------------------

B_CLOSED, B_ISOLATED, B_HALF_OPEN = 0, 1, 2


class BreakerModel:
    """State = (mode, duration_level, half_open_successes).

    ``duration_level`` walks min..max by doubling (the exponential
    isolation); ``half_open_successes`` counts the clean-traffic window
    that makes a recovery durable (resetting the level to min).

    Mutations:

    - ``reset_keeps_broken``: revive does not clear the broken flag —
      the node can never serve again (the checker's reachability pass
      reports every isolated state as unrevivable).
    - ``no_duration_reset``: a durable recovery keeps the doubled
      duration — violating the "durable recovery resets to min"
      safety property encoded in ``invariant``.
    - ``no_revive_timer``: isolation never arms a revive transition —
      the pre-PR-3-review bug class (extended deadlines without a fresh
      timer left idle channels isolated); isolated states deadlock.
    """

    name = "circuit_breaker"
    source = "incubator_brpc_tpu/rpc/circuit_breaker.py"

    def __init__(
        self,
        min_level: int = 1,
        max_level: int = 8,
        window: int = 2,
        reset_keeps_broken: bool = False,
        no_duration_reset: bool = False,
        no_revive_timer: bool = False,
    ):
        self.min_level = min_level
        self.max_level = max_level
        self.window = window
        self.reset_keeps_broken = reset_keeps_broken
        self.no_duration_reset = no_duration_reset
        self.no_revive_timer = no_revive_timer

    def initial_state(self):
        return (B_CLOSED, self.min_level, 0)

    def is_terminal(self, s) -> bool:
        return False  # the breaker runs forever; liveness is reachability

    def actions(self, s) -> List[Tuple[str, tuple]]:
        mode, level, succ = s
        out: List[Tuple[str, tuple]] = []
        if mode == B_CLOSED:
            out.append(("success", (B_CLOSED, level, 0)))
            # trip from closed: isolate at the CURRENT level (doubling
            # punishes only re-trips before a durable recovery)
            out.append(("trip", (B_ISOLATED, level, 0)))
        elif mode == B_ISOLATED:
            revived = (
                B_HALF_OPEN if not self.reset_keeps_broken else B_ISOLATED,
                level,
                0,
            )
            if not self.no_revive_timer:
                out.append(("elapse", revived))
                # early revival: the socket health-check proved the peer
                # back before the window ran out (Socket.on_revived)
                out.append(("early_revive", revived))
        else:  # B_HALF_OPEN
            nsucc = succ + 1
            if nsucc >= self.window:
                lvl = level if self.no_duration_reset else self.min_level
                out.append(("durable_recovery", (B_CLOSED, lvl, 0)))
            else:
                out.append(("success", (B_HALF_OPEN, level, nsucc)))
            out.append(
                ("retrip",
                 (B_ISOLATED, min(level * 2, self.max_level), 0))
            )
        return out

    def invariant(self, s) -> str:
        mode, level, succ = s
        if level > self.max_level:
            return f"isolation duration level {level} exceeds the cap"
        if mode == B_CLOSED and level != self.min_level:
            return (
                f"closed (durably recovered) at duration level {level} — "
                "a durable recovery must reset the penalty to the minimum"
            )
        return ""

    def terminal_ok(self, s) -> str:
        return ""

    # goal set for the reachability (revivability) check
    def is_goal(self, s) -> bool:
        return s[0] == B_CLOSED
