"""Extracted protocol models for the explicit-state checker.

A model is a hand-extracted, exhaustively-explorable twin of a protocol
implemented in the package.  Extraction rules (see docs/ANALYSIS.md,
"writing a model for the checker"):

- State is a flat immutable tuple — every field that influences a
  branch in the real code, nothing that doesn't (payload bytes,
  latencies and ids are abstracted away; *counts and phases* stay).
- Every nondeterministic choice the real system faces (message
  delivery order, drops, duplicates, timer firings, party deaths) is an
  explicit ``actions()`` branch, so the explorer visits ALL
  interleavings that the bounded scope admits — the substitute for
  production soak.
- Known-bad variants are constructor flags (``drop_close_echo=True``),
  NOT separate models: the meta-tests instantiate the mutation and
  assert the checker flips red, proving the property actually binds.

Two models ship:

- :class:`SessionModel` — the mc_dispatch N-party session protocol
  (parallel/mc_dispatch.py): accept fan-out + barrier, the monotone
  ``final = max(proposed, all targets)`` join, run fan-out into the
  LOCKSTEP BARRIER (a party that entered its chain is blocked until
  every party joins — the device collective), the convergent close
  barrier where every party echoes ``final``, and the fault plane: up
  to ``max_deaths`` parties may die at any instant; the proposer
  detects an outstanding dead party (the failed-RPC / socket feedback
  of the real code) and broadcasts ABORT so every survivor leaves the
  barrier — the abort-convergence property asserts no living party is
  ever left stuck in the barrier at the end.  The environment may
  reorder (inherent — delivery picks any in-flight message), drop
  (≤ ``max_drops``) and duplicate (≤ ``max_dups``) messages.  The
  proposer may time out ONLY when something was actually dropped — so
  a deadlock on a drop-free, death-free path is a protocol bug, not an
  abstracted timeout.
- :class:`BreakerModel` — the circuit-breaker state machine
  (rpc/circuit_breaker.py + the LB isolation dance in lb/__init__.py):
  closed → trip → isolated → (elapse | early socket revive) →
  half_open → (window successes → closed with duration reset) |
  (error → re-trip with doubled, capped duration).
"""

from __future__ import annotations

from typing import List, Tuple

# ---------------------------------------------------------------------------
# mc_dispatch session protocol
# ---------------------------------------------------------------------------

# party phases
#   RUNNING = inside the lockstep barrier (entered the jitted chain; in a
#   real multi-controller run the party is BLOCKED here until every other
#   party joins — or the abort plane unwedges it)
P_IDLE, P_ACCEPTED, P_RUNNING, P_RAN, P_ABORTED = 0, 1, 2, 3, 4
# proposer phases
PR_ACCEPT_WAIT, PR_RUN_WAIT, PR_DONE, PR_ABORTED = 0, 1, 2, 3

REJECT = -1  # run_resp payload for a below-floor run proposal


class SessionModel:
    """State = (proposer_phase, final, acks, echoes, parties, msgs,
    drops_used, dups_used, dead, deaths_used) — all tuples/ints,
    hashable.

    - ``acks``/``echoes``: tuples of per-party values (None until heard).
    - ``parties``: tuple of (phase, target_or_ran_steps).
    - ``msgs``: sorted tuple of in-flight (kind, party, value) triples —
      a multiset; delivery picks ANY element, which IS reorder.
      Delivery to a dead party consumes the message silently.
    - ``dead``: tuple of per-party death flags (the environment may kill
      up to ``max_deaths`` parties at any instant).

    Mutations (each one seeded bug the meta-tests prove the checker
    catches):

    - ``drop_close_echo``: parties that completed the collective never
      send the close-barrier echo — the real-code analog of a
      lost/forgotten ``run_resp``; the proposer waits forever on a
      drop-free path.
    - ``min_join``: the proposer folds accept targets with ``min``
      instead of ``max`` — a party with a higher floor gets a run
      proposal below what it accepted and rejects (the run-phase floor
      check mc_dispatch enforces), so a drop-free session aborts.
    - ``no_floor_reject``: with ``min_join``, parties also skip the
      floor check and silently run fewer steps than they accepted —
      the close barrier then sees non-convergent echoes.
    - ``drop_abort``: the proposer aborts (death detected, reject,
      timeout) but the ABORT BROADCAST is never sent — survivors stay
      wedged in the lockstep barrier forever; the abort-convergence
      check in ``terminal_ok`` flips red with the stuck party named.
    """

    name = "mc_dispatch_session"
    source = "incubator_brpc_tpu/parallel/mc_dispatch.py"

    M_ACCEPT_REQ, M_ACCEPT_ACK, M_RUN_REQ, M_RUN_RESP, M_ABORT = 0, 1, 2, 3, 4

    def __init__(
        self,
        n_parties: int = 3,
        steps: int = 2,
        floors: Tuple[int, ...] = (0, 1, 3),
        max_drops: int = 1,
        max_dups: int = 1,
        max_deaths: int = 0,
        drop_close_echo: bool = False,
        min_join: bool = False,
        no_floor_reject: bool = False,
        drop_abort: bool = False,
    ):
        assert len(floors) == n_parties
        self.n = n_parties
        self.steps = steps
        self.floors = floors
        self.max_drops = max_drops
        self.max_dups = max_dups
        self.max_deaths = max_deaths
        self.drop_close_echo = drop_close_echo
        self.min_join = min_join
        self.no_floor_reject = no_floor_reject
        self.drop_abort = drop_abort
        if max_deaths > 0:
            self.name = "mc_dispatch_session_party_death"

    def initial_state(self):
        msgs = tuple(
            sorted((self.M_ACCEPT_REQ, i, self.steps) for i in range(self.n))
        )
        return (
            PR_ACCEPT_WAIT,
            0,                                  # final (0 = not joined yet)
            (None,) * self.n,                   # accept acks
            (None,) * self.n,                   # close echoes
            ((P_IDLE, 0),) * self.n,
            msgs,
            0,                                  # drops used
            0,                                  # dups used
            (False,) * self.n,                  # dead flags
            0,                                  # deaths used
        )

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _without(msgs, m):
        out = list(msgs)
        out.remove(m)
        return tuple(out)

    @staticmethod
    def _with(msgs, *new):
        return tuple(sorted(msgs + tuple(new)))

    def _abort_msgs(self, dead):
        """The abort broadcast: one M_ABORT per living party (the real
        proposer skips parties it already observed dead).  The
        ``drop_abort`` mutation loses the whole broadcast."""
        if self.drop_abort:
            return ()
        return tuple(
            (self.M_ABORT, j, 0) for j in range(self.n) if not dead[j]
        )

    def is_terminal(self, s) -> bool:
        phase, _f, _a, _e, _p, msgs, _d, _du, _dead, _dt = s
        return phase in (PR_DONE, PR_ABORTED) and not msgs

    def actions(self, s) -> List[Tuple[str, tuple]]:
        (phase, final, acks, echoes, parties, msgs, drops, dups, dead,
         deaths) = s
        out: List[Tuple[str, tuple]] = []
        for m in sorted(set(msgs)):
            out.append((f"deliver{m}", self._deliver(s, m)))
            if m[0] == self.M_ABORT:
                # abort delivery is modeled RELIABLE: in the real code a
                # lost abort rpc is backstopped by each party's own
                # session deadline (every party unwedges itself); were
                # drops allowed here, that backstop would have to be
                # modeled too and the broadcast property would go
                # vacuous.  What this model verifies instead is the
                # sharper claim: every abort path SENDS an abort to
                # every survivor (the drop_abort mutation breaks it).
                continue
            if drops < self.max_drops:
                out.append(
                    (f"drop{m}",
                     (phase, final, acks, echoes, parties,
                      self._without(msgs, m), drops + 1, dups, dead, deaths))
                )
            if dups < self.max_dups:
                out.append(
                    (f"dup{m}",
                     (phase, final, acks, echoes, parties,
                      self._with(msgs, m), drops, dups + 1, dead, deaths))
                )
        # the environment kills a party at any instant
        if deaths < self.max_deaths:
            for j in range(self.n):
                if not dead[j]:
                    out.append(
                        (f"die{j}",
                         (phase, final, acks, echoes, parties, msgs, drops,
                          dups,
                          dead[:j] + (True,) + dead[j + 1:], deaths + 1))
                    )
        # the lockstep collective completes only when EVERY party joined
        # the barrier alive — then all emit their close echoes at once
        if all(p[0] == P_RUNNING for p in parties) and not any(dead):
            newp = tuple((P_RAN, p[1]) for p in parties)
            newm = msgs
            if not self.drop_close_echo:
                newm = self._with(
                    msgs,
                    *[(self.M_RUN_RESP, j, parties[j][1])
                      for j in range(self.n)],
                )
            out.append(
                ("collective_complete",
                 (phase, final, acks, echoes, newp, newm, drops, dups, dead,
                  deaths))
            )
        # death detection (the real code's failed-RPC / dying-socket
        # feedback): a dead party the proposer still waits on triggers
        # the fabric-wide abort — broadcast + local abort state
        if phase in (PR_ACCEPT_WAIT, PR_RUN_WAIT):
            waiting_on_dead = any(
                dead[j]
                and (acks[j] is None if phase == PR_ACCEPT_WAIT
                     else echoes[j] is None)
                for j in range(self.n)
            )
            if waiting_on_dead:
                out.append(
                    ("detect_death",
                     (PR_ABORTED, final, acks, echoes, parties,
                      self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                      dead, deaths))
                )
        # the proposer's deadline: enabled only when the environment
        # actually lost something — a drop-free path must make progress
        # through protocol actions alone.  A timeout abort broadcasts
        # too (the real session deadline does).
        if phase in (PR_ACCEPT_WAIT, PR_RUN_WAIT) and drops > 0:
            out.append(
                ("timeout",
                 (PR_ABORTED, final, acks, echoes, parties,
                  self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                  dead, deaths))
            )
        return out

    def _deliver(self, s, m) -> tuple:
        (phase, final, acks, echoes, parties, msgs, drops, dups, dead,
         deaths) = s
        msgs = self._without(msgs, m)
        kind, i, val = m
        same = (phase, final, acks, echoes, parties, msgs, drops, dups, dead,
                deaths)

        if kind == self.M_ABORT:
            # a survivor leaves whatever pre-completion phase it is in —
            # including the lockstep barrier, the whole point of the
            # broadcast; a party that already RAN keeps its result
            if dead[i]:
                return same
            pphase, val0 = parties[i]
            if pphase in (P_IDLE, P_ACCEPTED, P_RUNNING):
                parties = (
                    parties[:i] + ((P_ABORTED, val0),) + parties[i + 1:]
                )
            return (phase, final, acks, echoes, parties, msgs, drops, dups,
                    dead, deaths)

        if kind == self.M_ACCEPT_REQ:
            if dead[i]:
                return same  # delivered to a corpse: consumed, no ack
            # party admission: its ack may RAISE the target to its floor
            # (mc_dispatch_min_steps); duplicates re-ack idempotently
            target = max(val, self.floors[i])
            pphase, _ = parties[i]
            newp = parties
            if pphase == P_IDLE:
                newp = (
                    parties[:i] + ((P_ACCEPTED, target),) + parties[i + 1:]
                )
            elif pphase == P_ABORTED:
                return same  # aborted party re-joins nothing
            msgs = self._with(msgs, (self.M_ACCEPT_ACK, i, target))
            return (phase, final, acks, echoes, newp, msgs, drops, dups,
                    dead, deaths)

        if kind == self.M_ACCEPT_ACK:
            if phase != PR_ACCEPT_WAIT or acks[i] is not None:
                return same
            acks = acks[:i] + (val,) + acks[i + 1:]
            if all(a is not None for a in acks):
                # the N-party join: monotone max (the seeded min_join
                # mutation folds with min — non-monotone, violating what
                # parties accepted)
                fold = min if self.min_join else max
                final = fold(self.steps, *[a for a in acks])
                msgs = self._with(
                    msgs,
                    *[(self.M_RUN_REQ, j, final) for j in range(self.n)],
                )
                return (
                    PR_RUN_WAIT, final, acks, echoes, parties, msgs, drops,
                    dups, dead, deaths,
                )
            return (phase, final, acks, echoes, parties, msgs, drops, dups,
                    dead, deaths)

        if kind == self.M_RUN_REQ:
            if dead[i]:
                return same
            pphase, target = parties[i]
            if pphase == P_ACCEPTED:
                if val < self.floors[i] and not self.no_floor_reject:
                    # run proposal below this party's accepted floor:
                    # clean reject on the control stream
                    msgs = self._with(msgs, (self.M_RUN_RESP, i, REJECT))
                    return (
                        phase, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths,
                    )
                # the party enters its lockstep chain and BLOCKS in the
                # collective barrier until everyone joins (or abort)
                parties = (
                    parties[:i] + ((P_RUNNING, val),) + parties[i + 1:]
                )
                return (phase, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths)
            if pphase == P_RAN:
                # duplicate run proposal: idempotent re-echo of what ran
                if not self.drop_close_echo:
                    msgs = self._with(
                        msgs, (self.M_RUN_RESP, i, parties[i][1])
                    )
                return (phase, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths)
            # idle (run before accept cannot happen — the ack caused the
            # fan-out), running (duplicate), or aborted: ignored
            return same

        # M_RUN_RESP
        if phase != PR_RUN_WAIT or echoes[i] is not None:
            return same
        if val == REJECT:
            # a reject aborts the whole session — and the survivors
            # already in the barrier must be told (abort broadcast),
            # exactly like a death
            return (PR_ABORTED, final, acks, echoes, parties,
                    self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                    dead, deaths)
        echoes = echoes[:i] + (val,) + echoes[i + 1:]
        if all(e is not None for e in echoes):
            ok = all(e == final for e in echoes)
            if ok:
                return (PR_DONE, final, acks, echoes, parties, msgs, drops,
                        dups, dead, deaths)
            # non-convergent close: abort, and unwedge everyone
            return (PR_ABORTED, final, acks, echoes, parties,
                    self._with(msgs, *self._abort_msgs(dead)), drops, dups,
                    dead, deaths)
        return (phase, final, acks, echoes, parties, msgs, drops, dups,
                dead, deaths)

    # -- properties ----------------------------------------------------------

    def invariant(self, s) -> str:
        """Safety on every reachable state; '' when fine."""
        _ph, final, _a, _e, parties, _m, _d, _du, _dead, _dt = s
        for i, (pphase, val) in enumerate(parties):
            if pphase == P_RAN and val < self.floors[i]:
                return (
                    f"party {i} ran {val} steps, below its accepted floor "
                    f"{self.floors[i]} — the join was not monotone"
                )
        return ""

    def terminal_ok(self, s) -> str:
        """Checked on terminal states; '' when fine."""
        (phase, final, _a, echoes, parties, _m, drops, _du, dead,
         deaths) = s
        # abort convergence: however the session ended, no LIVING party
        # may be left inside the lockstep barrier — that is a real
        # process wedged on a device collective forever
        for i, (pphase, _v) in enumerate(parties):
            if pphase == P_RUNNING and not dead[i]:
                return (
                    f"party {i} is alive and still stuck in the lockstep "
                    "barrier at session end — the abort never reached it"
                )
        if phase == PR_DONE:
            expect = max(self.steps, *self.floors)
            if final != expect:
                return (
                    f"session closed with final={final}, but the monotone "
                    f"join of proposed={self.steps} and floors="
                    f"{self.floors} is {expect}"
                )
            for i, (pphase, ran) in enumerate(parties):
                if pphase != P_RAN or ran != final:
                    return (
                        f"close converged but party {i} state is "
                        f"{(pphase, ran)}, expected ran {final}"
                    )
        if drops == 0 and deaths == 0 and phase != PR_DONE:
            return (
                "drop-free, death-free path ended without a converged "
                f"close (proposer phase {phase}) — the protocol aborted "
                "or diverged with no environment fault to blame"
            )
        return ""


# ---------------------------------------------------------------------------
# mc_dispatch elastic resume protocol (the session model's resume scope)
# ---------------------------------------------------------------------------

# proposer phases of the resume scope
R_RUN_WAIT, R_RESUME_WAIT, R_RUN2_WAIT, R_DONE, R_ABORTED = 0, 1, 2, 3, 4
P_SPARE = 5  # a standby party outside the session


class ResumeSessionModel:
    """The elastic half of the session protocol
    (parallel/mc_dispatch.py's checkpoint/resume/replacement plane),
    modeled at step granularity:

    - Parties run the lockstep chain one step at a time
      (``collective_step`` fires only when EVERY slot's party is alive,
      running, at the same step, in the same epoch — the device
      collective's barrier).  Each party CHECKPOINTS nondeterministically
      (``checkpoint_i`` lifts its watermark to its current step) — the
      real code retains dispatch-time buffers whose READINESS lags, so
      watermark skew across parties is inherent, not an error.
    - The environment may kill ≤ ``max_deaths`` parties and drop ≤
      ``max_drops`` messages (abort delivery stays reliable, as in the
      base model — each party's own deadline is the real backstop).
    - On detecting a death the proposer broadcasts ABORT (stamped with
      the run EPOCH: stale aborts must not kill the healed run), then —
      when a spare party is available — runs the RESUME BARRIER: query
      every survivor's watermark, fold them with ``min`` (the dual of
      the accept phase's max-join: a session can only resume from a
      step EVERY survivor retained), bind the spare into the dead slot
      bootstrapped at the resume point, and re-run epoch 1 from there.

    Properties (each with a seeded mutation that flips it red):

    - ``no_resume_timeout``: the resume barrier loses its drop backstop
      — one dropped query/ack wedges the proposer forever
      (``model-stuck`` under ≤1 death + ≤1 drop).
    - ``max_resume_join``: the proposer folds watermarks with ``max`` —
      the resume point exceeds some survivor's last checkpoint
      (``model-unsafe``: resume point must be the min-join).
    - ``skip_replacement``: the dead slot is never filled and survivors
      step anyway — a resumed session re-runs steps with a DIVERGENT
      party set, which for an axis-reducing kernel silently changes the
      math (``model-unsafe``).
    """

    name = "mc_dispatch_session_resume"
    source = "incubator_brpc_tpu/parallel/mc_dispatch.py"

    M_RUN, M_RESP, M_ABORT, M_QUERY, M_QACK = 0, 1, 2, 3, 4

    def __init__(
        self,
        n_parties: int = 3,
        steps: int = 3,
        max_drops: int = 1,
        max_deaths: int = 1,
        max_resume_join: bool = False,
        skip_replacement: bool = False,
        no_resume_timeout: bool = False,
    ):
        self.n = n_parties
        self.steps = steps
        self.max_drops = max_drops
        self.max_deaths = max_deaths
        self.max_resume_join = max_resume_join
        self.skip_replacement = skip_replacement
        self.no_resume_timeout = no_resume_timeout

    # State = (phase, resume_pt, qacks, echoes, parties, spare_free,
    #          msgs, drops, dead, deaths)
    # - parties: per-slot (pphase, done, watermark, epoch)
    # - qacks: per-slot survivor watermark answers while the resume
    #   barrier gathers; at the fold they collapse into ``resume_pt`` =
    #   (elected point, true min) and reset — keeping the whole answer
    #   vector alive through the resumed run would multiply the space
    #   for no property
    # - echoes: per-slot close echoes for the CURRENT epoch
    # - resume_pt: None until the resume barrier folded
    # - msgs: sorted multiset of (kind, slot, value); delivery picks any
    # Deaths are modeled at any instant the proposer still WAITS on the
    # session (a death after it settled is outside the protocol).

    def initial_state(self):
        msgs = tuple(
            sorted((self.M_RUN, i, (0, 0)) for i in range(self.n))
        )
        return (
            R_RUN_WAIT,
            None,
            (None,) * self.n,
            (None,) * self.n,
            ((P_ACCEPTED, 0, 0, 0),) * self.n,
            True,
            msgs,
            0,
            (False,) * self.n,
            0,
        )

    @staticmethod
    def _without(msgs, m):
        out = list(msgs)
        out.remove(m)
        return tuple(out)

    @staticmethod
    def _with(msgs, *new):
        return tuple(sorted(msgs + tuple(new)))

    def _abort_msgs(self, dead, epoch):
        return tuple(
            (self.M_ABORT, j, epoch) for j in range(self.n) if not dead[j]
        )

    def is_terminal(self, s) -> bool:
        phase, _r, _q, _e, _p, _sf, msgs, _d, _dead, _dt = s
        return phase in (R_DONE, R_ABORTED) and not msgs

    def _cur_epoch(self, phase) -> int:
        return 1 if phase in (R_RUN2_WAIT,) else 0

    def actions(self, s) -> List[Tuple[str, tuple]]:
        (phase, rpt, qacks, echoes, parties, spare_free, msgs, drops,
         dead, deaths) = s
        out: List[Tuple[str, tuple]] = []
        # Partial-order reduction for the post-abort drain: once the
        # proposer is R_ABORTED the plane is inert — every remaining
        # delivery commutes (the epoch tombstone makes abort/run order
        # irrelevant, RESP/QACK are ignored, QUERY answers don't change
        # party state), so ONE canonical delivery order suffices and
        # drops of never-read messages prove nothing.
        drain = phase == R_ABORTED
        for m in sorted(set(msgs)):
            out.append((f"deliver{m}", self._deliver(s, m)))
            if drain:
                break
            if m[0] != self.M_ABORT and drops < self.max_drops:
                out.append(
                    (f"drop{m}",
                     (phase, rpt, qacks, echoes, parties, spare_free,
                      self._without(msgs, m), drops + 1, dead, deaths))
                )
        # the environment kills a party at any instant the session is
        # still in flight
        if deaths < self.max_deaths and phase in (
            R_RUN_WAIT, R_RESUME_WAIT, R_RUN2_WAIT
        ):
            for j in range(self.n):
                if not dead[j]:
                    out.append(
                        (f"die{j}",
                         (phase, rpt, qacks, echoes, parties, spare_free,
                          msgs, drops,
                          dead[:j] + (True,) + dead[j + 1:], deaths + 1))
                    )
        # one lockstep step: every slot's party alive, running, at the
        # same step, in the same epoch.  The skip_replacement mutation
        # relaxes the barrier to the ALIVE slots only — the bug where a
        # "resumed" session quietly steps without the dead slot.  Each
        # party independently may or may not CHECKPOINT the completed
        # step (one branch per subset): the real rings retain
        # dispatch-time buffers whose readiness lags, so watermark skew
        # across parties is inherent — the min-join must absorb it.
        active = [
            (j, parties[j]) for j in range(self.n) if not dead[j]
        ]
        slots_ok = (not any(dead)) or self.skip_replacement
        if active and slots_ok:
            phases = {p[0] for _j, p in active}
            dones = {p[1] for _j, p in active}
            epochs = {p[3] for _j, p in active}
            if (
                phases == {P_RUNNING}
                and len(dones) == 1
                and len(epochs) == 1
                and next(iter(dones)) < self.steps
            ):
                done = next(iter(dones)) + 1
                # in the drain nobody will ever read a new checkpoint:
                # skip the ckpt-subset branching (state pollution only)
                masks = (0,) if drain else range(1 << len(active))
                for mask in masks:
                    newp = list(parties)
                    newm = msgs
                    for pos, (j, (pp, _d0, wm, pe)) in enumerate(active):
                        ckpt = done if mask & (1 << pos) else wm
                        if done == self.steps:
                            newp[j] = (P_RAN, done, ckpt, pe)
                            newm = self._with(
                                newm, (self.M_RESP, j, (done, pe))
                            )
                        else:
                            newp[j] = (P_RUNNING, done, ckpt, pe)
                    out.append(
                        (f"collective_step[ckpt_mask={mask}]",
                         (phase, rpt, qacks, echoes, tuple(newp),
                          spare_free, newm, drops, dead, deaths))
                    )
        # death detection → abort broadcast → resume barrier (with a
        # spare) or plain abort (without)
        if phase == R_RUN_WAIT:
            waiting_on_dead = any(
                dead[j] and echoes[j] is None for j in range(self.n)
            )
            if waiting_on_dead:
                aborts = self._abort_msgs(dead, 0)
                if spare_free:
                    queries = tuple(
                        (self.M_QUERY, j, 0)
                        for j in range(self.n)
                        if not dead[j]
                    )
                    out.append(
                        ("detect_death_resume",
                         (R_RESUME_WAIT, rpt, (None,) * self.n, echoes,
                          parties, spare_free,
                          self._with(msgs, *aborts, *queries), drops, dead,
                          deaths))
                    )
                else:
                    out.append(
                        ("detect_death_abort",
                         (R_ABORTED, rpt, qacks, echoes, parties,
                          spare_free, self._with(msgs, *aborts), drops,
                          dead, deaths))
                    )
        # the proposer's deadline: enabled only when the environment
        # actually lost something (a drop-free path must progress through
        # protocol actions alone).  The no_resume_timeout mutation strips
        # the backstop from the resume barrier — one dropped query/ack
        # then wedges the proposer forever.
        timeout_phases = [R_RUN_WAIT, R_RUN2_WAIT]
        if not self.no_resume_timeout:
            timeout_phases.append(R_RESUME_WAIT)
        if phase in timeout_phases and drops > 0:
            ep = self._cur_epoch(phase)
            out.append(
                ("timeout",
                 (R_ABORTED, rpt, qacks, echoes, parties, spare_free,
                  self._with(msgs, *self._abort_msgs(dead, ep)), drops,
                  dead, deaths))
            )
        return out

    def _deliver(self, s, m) -> tuple:
        (phase, rpt, qacks, echoes, parties, spare_free, msgs, drops,
         dead, deaths) = s
        msgs = self._without(msgs, m)
        kind, i, val = m
        same = (phase, rpt, qacks, echoes, parties, spare_free, msgs,
                drops, dead, deaths)

        if kind == self.M_ABORT:
            if dead[i]:
                return same
            pphase, done, wm, ep = parties[i]
            # epoch guard: a straggler abort from the superseded run must
            # not kill the healed run's party.  The abort also leaves its
            # epoch as a TOMBSTONE (ep = max(ep, abort epoch)): a run
            # proposal of an epoch ≤ it arriving later must not start a
            # zombie chain — the race the real code closes with
            # mc_dispatch's _aborted_epochs map.
            if ep > val:
                return same
            stone = max(ep, val)
            newphase = (
                P_ABORTED if pphase in (P_ACCEPTED, P_RUNNING) else pphase
            )
            # a left chain's progress counter is dead state: normalize it
            # so death-timing variants collapse (the watermark stays —
            # that ring is what a resume restores from)
            newdone = 0 if newphase == P_ABORTED else done
            parties = (
                parties[:i] + ((newphase, newdone, wm, stone),)
                + parties[i + 1:]
            )
            return (phase, rpt, qacks, echoes, parties, spare_free, msgs,
                    drops, dead, deaths)

        if kind == self.M_RUN:
            if dead[i]:
                return same
            start, ep = val
            pphase, done, wm, pep = parties[i]
            if pep > ep or pphase == P_SPARE:
                return same  # stale proposal for a superseded epoch
            if pphase in (P_ABORTED, P_RAN) and ep <= pep:
                # tombstoned (or already-completed) at this epoch: only a
                # genuinely newer run (the resume fan-out) re-enters
                return same
            if start > wm and start > 0:
                # asked to resume from a step this party never
                # checkpointed: clean reject (the min-join violation's
                # observable symptom)
                msgs = self._with(msgs, (self.M_RESP, i, (REJECT, ep)))
                return (phase, rpt, qacks, echoes, parties, spare_free,
                        msgs, drops, dead, deaths)
            if start >= self.steps:
                # resume point == the final step: zero steps to replay —
                # the party echoes straight from its checkpoint (the real
                # chain's empty range(resume_from, steps) loop)
                parties = (
                    parties[:i] + ((P_RAN, start, wm, ep),)
                    + parties[i + 1:]
                )
                msgs = self._with(msgs, (self.M_RESP, i, (start, ep)))
                return (phase, rpt, qacks, echoes, parties, spare_free,
                        msgs, drops, dead, deaths)
            parties = (
                parties[:i] + ((P_RUNNING, start, wm, ep),)
                + parties[i + 1:]
            )
            return (phase, rpt, qacks, echoes, parties, spare_free, msgs,
                    drops, dead, deaths)

        if kind == self.M_QUERY:
            if dead[i]:
                return same
            _pp, _d, wm, _ep = parties[i]
            msgs = self._with(msgs, (self.M_QACK, i, wm))
            return (phase, rpt, qacks, echoes, parties, spare_free, msgs,
                    drops, dead, deaths)

        if kind == self.M_QACK:
            if phase != R_RESUME_WAIT or qacks[i] is not None:
                return same
            qacks = qacks[:i] + (val,) + qacks[i + 1:]
            alive = [j for j in range(self.n) if not dead[j]]
            if all(qacks[j] is not None for j in alive):
                # the resume barrier folded: min-join over the survivor
                # watermarks (the max_resume_join mutation folds with max
                # — electing a step some survivor cannot restore).  The
                # answer vector collapses into (elected, true min): the
                # property lives on, the space doesn't.
                fold = max if self.max_resume_join else min
                point = fold(qacks[j] for j in alive)
                tmin = min(qacks[j] for j in alive)
                newp = list(parties)
                newdead = dead
                if not self.skip_replacement:
                    for j in range(self.n):
                        if dead[j]:
                            # the replacement: bootstrapped at the resume
                            # point (its watermark IS the fetched shard)
                            newp[j] = (P_ACCEPTED, 0, point, 1)
                            newdead = (
                                newdead[:j] + (False,) + newdead[j + 1:]
                            )
                    spare_free = False
                runs = tuple(
                    (self.M_RUN, j, (point, 1))
                    for j in range(self.n)
                    if not newdead[j]
                )
                return (
                    R_RUN2_WAIT, (point, tmin), (None,) * self.n,
                    (None,) * self.n, tuple(newp), spare_free,
                    self._with(msgs, *runs), drops, newdead, deaths,
                )
            return (phase, rpt, qacks, echoes, parties, spare_free, msgs,
                    drops, dead, deaths)

        # M_RESP
        steps_val, ep = val
        if (
            phase not in (R_RUN_WAIT, R_RUN2_WAIT)
            or ep != self._cur_epoch(phase)
            or echoes[i] is not None
        ):
            return same
        if steps_val == REJECT:
            return (R_ABORTED, rpt, qacks, echoes, parties, spare_free,
                    self._with(msgs, *self._abort_msgs(dead, ep)), drops,
                    dead, deaths)
        echoes = echoes[:i] + (steps_val,) + echoes[i + 1:]
        if all(e is not None for e in echoes):
            if all(e == self.steps for e in echoes):
                return (R_DONE, rpt, qacks, echoes, parties, spare_free,
                        msgs, drops, dead, deaths)
            return (R_ABORTED, rpt, qacks, echoes, parties, spare_free,
                    self._with(msgs, *self._abort_msgs(dead, ep)), drops,
                    dead, deaths)
        return (phase, rpt, qacks, echoes, parties, spare_free, msgs,
                drops, dead, deaths)

    # -- properties ----------------------------------------------------------

    def invariant(self, s) -> str:
        (phase, rpt, _q, _e, parties, _sf, _m, _d, dead, _dt) = s
        if rpt is not None:
            point, true_min = rpt
            if point != true_min:
                return (
                    f"resume point {point} is not the min-join over the "
                    f"survivor watermarks (true min {true_min}) — some "
                    "survivor never checkpointed the elected step"
                )
            if any(dead):
                for j, (pphase, done, _wm, ep) in enumerate(parties):
                    if not dead[j] and ep == 1 and done > point:
                        return (
                            f"resumed session re-ran step(s) past "
                            f"{point} with a divergent party set (dead "
                            "slot never replaced) — an axis-reducing "
                            "kernel silently changes its math"
                        )
        return ""

    def terminal_ok(self, s) -> str:
        (phase, _r, _q, echoes, parties, _sf, _m, drops, dead,
         deaths) = s
        for i, (pphase, _d0, _wm, _ep) in enumerate(parties):
            if pphase == P_RUNNING and not dead[i]:
                return (
                    f"party {i} is alive and still stuck in the lockstep "
                    "barrier at session end — the abort never reached it"
                )
        if phase == R_DONE:
            if any(e != self.steps for e in echoes):
                return (
                    f"session closed DONE with echoes {echoes}, expected "
                    f"every party to echo {self.steps}"
                )
        if drops == 0 and deaths == 0 and phase != R_DONE:
            return (
                "drop-free, death-free path ended without a converged "
                f"close (proposer phase {phase})"
            )
        if drops == 0 and deaths <= self.max_deaths and phase != R_DONE:
            return (
                f"a path with {deaths} death(s), zero drops and a spare "
                f"party available ended {phase} instead of healing — "
                "the elastic resume failed to complete"
            )
        return ""


# ---------------------------------------------------------------------------
# mc_dispatch overlap scheduler (chunked double-buffered sessions, T3)
# ---------------------------------------------------------------------------

# overlap-scope proposer phases
O_RUN_WAIT, O_DONE, O_ABORTED = 0, 1, 2


class OverlapSessionModel:
    """The chunked overlap schedule of the session run phase
    (parallel/mc_dispatch.py ``run_dispatch_session`` with ``chunks=C,
    double_buffer=True`` — docs/DEVICE_PLANE.md "overlap scheduler"),
    modeled at CHUNK granularity:

    - Each party dispatches sub-collectives in the schedule order (step
      k slice 0..C-1, then step k+1): ``dispatch_i`` advances a linear
      cursor.  The two-slot double buffer is the dispatch GATE: slice
      j of step k+1 dispatches only after the party OBSERVED the ack of
      step k's chunk j (at the device level this is the dataflow edge
      the real code relies on; the host never blocks).
    - A chunk (k, j) COMPLETES (``complete_j``) only when EVERY party is
      alive and has dispatched it — the per-chunk collective rendezvous
      — and completion is dataflow-ordered per slice, so a per-slice
      count suffices.
    - A party OBSERVES a chunk ack (``ack_i``) only after the chunk
      completed — the chunk-ack riding the step-ack discipline.
    - The environment kills ≤ ``max_deaths`` parties and drops ≤
      ``max_drops`` control messages at any instant — including mid-
      step with half a step's chunks acked (the torn-step scope); the
      proposer detects the death and broadcasts ABORT, which must
      unwedge every survivor whatever its cursor/ack skew.

    Mutations (the meta-tested seeded bugs):

    - ``ack_before_complete``: a party observes a chunk's ack as soon
      as it DISPATCHED it, not when the sub-collective completed — the
      overlap degenerates to unbounded pipelining and the ack no longer
      witnesses anything (``model-unsafe``: acked past completed).
    - ``no_ack_gate``: the dispatch of step k+1's slice j no longer
      waits for step k's chunk-j ack — more than two step slots in
      flight on one slice (``model-unsafe``: the double-buffer window
      invariant).
    """

    name = "mc_dispatch_session_overlap"
    source = "incubator_brpc_tpu/parallel/mc_dispatch.py"

    M_RUN, M_RESP, M_ABORT = 0, 1, 2

    def __init__(
        self,
        n_parties: int = 2,
        steps: int = 2,
        chunks: int = 2,
        max_drops: int = 1,
        max_deaths: int = 1,
        ack_before_complete: bool = False,
        no_ack_gate: bool = False,
    ):
        self.n = n_parties
        self.steps = steps
        self.chunks = chunks
        self.max_drops = max_drops
        self.max_deaths = max_deaths
        self.ack_before_complete = ack_before_complete
        self.no_ack_gate = no_ack_gate

    # State = (phase, echoes, parties, completed, msgs, drops, dead,
    #          deaths)
    # - parties[i] = (pphase, disp, acked): ``disp`` is the linear chunk
    #   cursor (chunk (k, j) dispatched iff disp > k*C + j), ``acked`` a
    #   per-slice tuple of observed-ack step counts
    # - completed[j] = consecutively completed chunks on slice j (the
    #   per-slice dataflow order makes a count exact)
    # - msgs: sorted multiset of (kind, party, value) control messages
    #   (the chunk plane itself is shared-state actions, not messages:
    #   it is the device fabric, not the rpc plane)

    def initial_state(self):
        msgs = tuple(
            sorted((self.M_RUN, i, self.steps) for i in range(self.n))
        )
        return (
            O_RUN_WAIT,
            (None,) * self.n,
            ((P_ACCEPTED, 0, (0,) * self.chunks),) * self.n,
            (0,) * self.chunks,
            msgs,
            0,
            (False,) * self.n,
            0,
        )

    @staticmethod
    def _without(msgs, m):
        out = list(msgs)
        out.remove(m)
        return tuple(out)

    @staticmethod
    def _with(msgs, *new):
        return tuple(sorted(msgs + tuple(new)))

    def _abort_msgs(self, dead):
        return tuple(
            (self.M_ABORT, j, 0) for j in range(self.n) if not dead[j]
        )

    def _dispatched(self, disp: int, k: int, j: int) -> bool:
        return disp > k * self.chunks + j

    def is_terminal(self, s) -> bool:
        phase, _e, _p, _c, msgs, _d, _dead, _dt = s
        return phase in (O_DONE, O_ABORTED) and not msgs

    def actions(self, s):
        (phase, echoes, parties, completed, msgs, drops, dead, deaths) = s
        out: List[Tuple[str, tuple]] = []
        total = self.steps * self.chunks
        for m in sorted(set(msgs)):
            out.append((f"deliver{m}", self._deliver(s, m)))
            # abort delivery stays reliable — each party's own deadline
            # is the real backstop, exactly as in the base model
            if m[0] != self.M_ABORT and drops < self.max_drops:
                out.append(
                    (f"drop{m}",
                     (phase, echoes, parties, completed,
                      self._without(msgs, m), drops + 1, dead, deaths))
                )
        if deaths < self.max_deaths and phase == O_RUN_WAIT:
            for j in range(self.n):
                if not dead[j]:
                    out.append(
                        (f"die{j}",
                         (phase, echoes, parties, completed, msgs, drops,
                          dead[:j] + (True,) + dead[j + 1:], deaths + 1))
                    )
        # per-party chunk-plane actions
        for i in range(self.n):
            if dead[i]:
                continue
            pphase, disp, acked = parties[i]
            if pphase != P_RUNNING:
                continue
            # dispatch the next sub-collective in schedule order, gated
            # by the two-slot double buffer: slice j of step k waits for
            # the OBSERVED ack of step k-1's chunk j (the no_ack_gate
            # mutation removes the wait)
            if disp < total:
                k, j = divmod(disp, self.chunks)
                if k == 0 or acked[j] >= k or self.no_ack_gate:
                    newp = (
                        parties[:i] + ((P_RUNNING, disp + 1, acked),)
                        + parties[i + 1:]
                    )
                    out.append(
                        (f"dispatch{i}[{k},{j}]",
                         (phase, echoes, newp, completed, msgs, drops,
                          dead, deaths))
                    )
            # observe a chunk ack: the completion of (acked[j], j) — the
            # ack_before_complete mutation lets a dispatched chunk ack
            # without its collective having completed
            for j in range(self.chunks):
                a = acked[j]
                if a >= self.steps or not self._dispatched(disp, a, j):
                    continue
                if a < completed[j] or self.ack_before_complete:
                    newa = acked[:j] + (a + 1,) + acked[j + 1:]
                    full = (
                        disp == total
                        and all(
                            newa[q] == self.steps
                            for q in range(self.chunks)
                        )
                    )
                    newph = P_RAN if full else P_RUNNING
                    newp = (
                        parties[:i] + ((newph, disp, newa),)
                        + parties[i + 1:]
                    )
                    newm = msgs
                    if full:
                        newm = self._with(
                            msgs, (self.M_RESP, i, self.steps)
                        )
                    out.append(
                        (f"ack{i}[{a},{j}]",
                         (phase, echoes, newp, completed, newm, drops,
                          dead, deaths))
                    )
        # chunk completion: the per-chunk collective rendezvous — every
        # party alive and dispatched, per-slice dataflow order
        if not any(dead):
            for j in range(self.chunks):
                k = completed[j]
                if k >= self.steps:
                    continue
                if all(
                    p[0] in (P_RUNNING, P_RAN)
                    and self._dispatched(p[1], k, j)
                    for p in parties
                ):
                    newc = (
                        completed[:j] + (k + 1,) + completed[j + 1:]
                    )
                    out.append(
                        (f"complete[{k},{j}]",
                         (phase, echoes, parties, newc, msgs, drops,
                          dead, deaths))
                    )
        # death detection: a dead party the proposer still waits on
        # triggers the fabric-wide abort broadcast
        if phase == O_RUN_WAIT:
            if any(
                dead[j] and echoes[j] is None for j in range(self.n)
            ):
                out.append(
                    ("detect_death",
                     (O_ABORTED, echoes, parties, completed,
                      self._with(msgs, *self._abort_msgs(dead)), drops,
                      dead, deaths))
                )
        # deadline backstop: only when the environment actually lost
        # something — a drop-free path must progress on its own
        if phase == O_RUN_WAIT and drops > 0:
            out.append(
                ("timeout",
                 (O_ABORTED, echoes, parties, completed,
                  self._with(msgs, *self._abort_msgs(dead)), drops, dead,
                  deaths))
            )
        return out

    def _deliver(self, s, m) -> tuple:
        (phase, echoes, parties, completed, msgs, drops, dead, deaths) = s
        msgs = self._without(msgs, m)
        kind, i, val = m
        same = (phase, echoes, parties, completed, msgs, drops, dead,
                deaths)

        if kind == self.M_ABORT:
            if dead[i]:
                return same
            pphase, disp, acked = parties[i]
            if pphase in (P_ACCEPTED, P_RUNNING):
                # mid-step, half-acked, whatever: the survivor leaves
                # its chunk pipeline; cursor state is dead — normalize
                # so death-timing variants collapse
                parties = (
                    parties[:i]
                    + ((P_ABORTED, 0, (0,) * self.chunks),)
                    + parties[i + 1:]
                )
            return (phase, echoes, parties, completed, msgs, drops, dead,
                    deaths)

        if kind == self.M_RUN:
            if dead[i]:
                return same
            pphase, disp, acked = parties[i]
            if pphase == P_ACCEPTED:
                parties = (
                    parties[:i] + ((P_RUNNING, disp, acked),)
                    + parties[i + 1:]
                )
            return (phase, echoes, parties, completed, msgs, drops, dead,
                    deaths)

        # M_RESP
        if phase != O_RUN_WAIT or echoes[i] is not None:
            return same
        echoes = echoes[:i] + (val,) + echoes[i + 1:]
        if all(e is not None for e in echoes):
            if all(e == self.steps for e in echoes):
                return (O_DONE, echoes, parties, completed, msgs, drops,
                        dead, deaths)
            return (O_ABORTED, echoes, parties, completed,
                    self._with(msgs, *self._abort_msgs(dead)), drops,
                    dead, deaths)
        return (phase, echoes, parties, completed, msgs, drops, dead,
                deaths)

    # -- properties ----------------------------------------------------------

    def invariant(self, s) -> str:
        (_ph, _e, parties, completed, _m, _d, dead, _dt) = s
        for i, (pphase, disp, acked) in enumerate(parties):
            if dead[i] or pphase not in (P_RUNNING, P_RAN):
                continue
            for j in range(self.chunks):
                if acked[j] > completed[j]:
                    return (
                        f"party {i} observed the ack of step "
                        f"{acked[j] - 1} chunk {j} before the "
                        "sub-collective completed — a chunk ack must "
                        "witness completion"
                    )
                # steps whose chunk j this party has dispatched
                ds = max(0, (disp - j - 1) // self.chunks + 1)
                if ds > acked[j] + 1:
                    return (
                        f"party {i} dispatched step {ds - 1}'s chunk "
                        f"{j} with only {acked[j]} acks observed on "
                        "that slice — more than two step slots in "
                        "flight (the double-buffer window)"
                    )
        return ""

    def terminal_ok(self, s) -> str:
        (phase, echoes, parties, _c, _m, drops, dead, deaths) = s
        for i, (pphase, _disp, _acked) in enumerate(parties):
            if pphase == P_RUNNING and not dead[i]:
                return (
                    f"party {i} is alive and still inside its chunk "
                    "pipeline at session end — the abort never reached "
                    "it (half-acked step left wedged)"
                )
        if phase == O_DONE:
            for i, (pphase, disp, acked) in enumerate(parties):
                if pphase != P_RAN or any(
                    a != self.steps for a in acked
                ):
                    return (
                        f"close converged but party {i} ended "
                        f"{(pphase, disp, acked)} — not every chunk "
                        "acked"
                    )
        if drops == 0 and deaths == 0 and phase != O_DONE:
            return (
                "drop-free, death-free path ended without a converged "
                f"close (proposer phase {phase})"
            )
        return ""


# ---------------------------------------------------------------------------
# circuit-breaker state machine
# ---------------------------------------------------------------------------

B_CLOSED, B_ISOLATED, B_HALF_OPEN = 0, 1, 2


class BreakerModel:
    """State = (mode, duration_level, half_open_successes).

    ``duration_level`` walks min..max by doubling (the exponential
    isolation); ``half_open_successes`` counts the clean-traffic window
    that makes a recovery durable (resetting the level to min).

    Mutations:

    - ``reset_keeps_broken``: revive does not clear the broken flag —
      the node can never serve again (the checker's reachability pass
      reports every isolated state as unrevivable).
    - ``no_duration_reset``: a durable recovery keeps the doubled
      duration — violating the "durable recovery resets to min"
      safety property encoded in ``invariant``.
    - ``no_revive_timer``: isolation never arms a revive transition —
      the pre-PR-3-review bug class (extended deadlines without a fresh
      timer left idle channels isolated); isolated states deadlock.
    """

    name = "circuit_breaker"
    source = "incubator_brpc_tpu/rpc/circuit_breaker.py"

    def __init__(
        self,
        min_level: int = 1,
        max_level: int = 8,
        window: int = 2,
        reset_keeps_broken: bool = False,
        no_duration_reset: bool = False,
        no_revive_timer: bool = False,
    ):
        self.min_level = min_level
        self.max_level = max_level
        self.window = window
        self.reset_keeps_broken = reset_keeps_broken
        self.no_duration_reset = no_duration_reset
        self.no_revive_timer = no_revive_timer

    def initial_state(self):
        return (B_CLOSED, self.min_level, 0)

    def is_terminal(self, s) -> bool:
        return False  # the breaker runs forever; liveness is reachability

    def actions(self, s) -> List[Tuple[str, tuple]]:
        mode, level, succ = s
        out: List[Tuple[str, tuple]] = []
        if mode == B_CLOSED:
            out.append(("success", (B_CLOSED, level, 0)))
            # trip from closed: isolate at the CURRENT level (doubling
            # punishes only re-trips before a durable recovery)
            out.append(("trip", (B_ISOLATED, level, 0)))
        elif mode == B_ISOLATED:
            revived = (
                B_HALF_OPEN if not self.reset_keeps_broken else B_ISOLATED,
                level,
                0,
            )
            if not self.no_revive_timer:
                out.append(("elapse", revived))
                # early revival: the socket health-check proved the peer
                # back before the window ran out (Socket.on_revived)
                out.append(("early_revive", revived))
        else:  # B_HALF_OPEN
            nsucc = succ + 1
            if nsucc >= self.window:
                lvl = level if self.no_duration_reset else self.min_level
                out.append(("durable_recovery", (B_CLOSED, lvl, 0)))
            else:
                out.append(("success", (B_HALF_OPEN, level, nsucc)))
            out.append(
                ("retrip",
                 (B_ISOLATED, min(level * 2, self.max_level), 0))
            )
        return out

    def invariant(self, s) -> str:
        mode, level, succ = s
        if level > self.max_level:
            return f"isolation duration level {level} exceeds the cap"
        if mode == B_CLOSED and level != self.min_level:
            return (
                f"closed (durably recovered) at duration level {level} — "
                "a durable recovery must reset the penalty to the minimum"
            )
        return ""

    def terminal_ok(self, s) -> str:
        return ""

    # goal set for the reachability (revivability) check
    def is_goal(self, s) -> bool:
        return s[0] == B_CLOSED
