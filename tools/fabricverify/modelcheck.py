"""Explicit-state model checker — small-scope exhaustive exploration.

The repo's multi-party protocols (the mc_dispatch session dance, the
circuit breaker's trip/revive machine) are proven in tests on a handful
of *happy* interleavings; the reference substitutes years of production
soak.  This checker substitutes *exhaustion at small scope*: every
reachable state of a bounded model (3 parties, 2 steps, ≤1 drop, ≤1
duplicate — thousands of states) is visited, and three property classes
are asserted on ALL of them:

- **no stuck state** (``model-stuck``): every reachable non-terminal
  state has at least one enabled action.  A deadlock on a path with no
  environment drops is a protocol bug, full stop.
- **safety** (``model-unsafe``): the model's ``invariant`` holds in
  every reachable state and ``terminal_ok`` in every terminal one
  (close convergence, monotone join, duration caps, durable-recovery
  reset).
- **revivability** (``model-unrevivable``): for models with a goal set
  (the breaker's CLOSED), the goal is reachable from EVERY reachable
  state — no one-way door into permanent isolation.

Violations are anchored at the *modeled source file* (the protocol the
model extracts), with the counterexample trace in the message — the
checker's red is a statement about the protocol as implemented, and the
fix belongs there (or, if the model itself drifted from the code, in
models.py; either way the tree stays red until they agree).

Standalone: ``python -m tools.fabricverify.modelcheck`` (the
``make verify-models`` entry) prints per-model state counts — the
explored-space size is part of the test log so a collapsed exploration
(a model accidentally gutted to three states) is visible in review.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tools.fabricverify import REPO_ROOT, Violation
from tools.fabricverify.models import (
    BreakerModel,
    OverlapSessionModel,
    ResumeSessionModel,
    SessionModel,
)

_MAX_STATES = 500_000  # runaway-model backstop, far above the bounded scopes


@dataclass
class Result:
    model_name: str
    states: int = 0
    transitions: int = 0
    violations: List[Violation] = field(default_factory=list)
    # state -> (predecessor state, action label) for counterexample traces
    parent: Dict[tuple, Tuple[Optional[tuple], str]] = field(
        default_factory=dict
    )

    def trace(self, state: tuple, limit: int = 12) -> str:
        labels: List[str] = []
        cur = state
        while cur in self.parent and len(labels) < 64:
            prev, label = self.parent[cur]
            if prev is None:
                break
            labels.append(label)
            cur = prev
        labels.reverse()
        if len(labels) > limit:
            labels = labels[:3] + [f"... {len(labels) - 6} steps ..."] + labels[-3:]
        return " -> ".join(labels) if labels else "<initial>"


def _anchor(model) -> Tuple[str, int]:
    src = getattr(model, "source", None)
    if src:
        return os.path.join(REPO_ROOT, src), 1
    import tools.fabricverify.models as m

    return m.__file__, 1


def explore(model, max_states: int = _MAX_STATES) -> Result:
    """BFS the full reachable space, checking properties as states are
    discovered (the counterexample is then a shortest path)."""

    res = Result(model_name=model.name)
    path, line = _anchor(model)
    init = model.initial_state()
    frontier = [init]
    res.parent[init] = (None, "")
    seen = {init}

    def report(rule: str, state: tuple, msg: str) -> None:
        res.violations.append(
            Violation(
                rule, path, line,
                f"[{model.name}] {msg} (trace: {res.trace(state)})",
            )
        )

    while frontier:
        nxt: List[tuple] = []
        for s in frontier:
            res.states += 1
            bad = model.invariant(s)
            if bad:
                report("model-unsafe", s, bad)
                continue  # don't expand past a safety violation
            terminal = model.is_terminal(s)
            acts = model.actions(s)
            if terminal:
                tbad = model.terminal_ok(s)
                if tbad:
                    report("model-unsafe", s, tbad)
                continue
            if not acts:
                report(
                    "model-stuck", s,
                    "reachable state has no enabled action — the protocol "
                    "is deadlocked with no environment fault pending",
                )
                continue
            for label, s2 in acts:
                res.transitions += 1
                if s2 not in seen:
                    seen.add(s2)
                    res.parent[s2] = (s, label)
                    nxt.append(s2)
            if res.states + len(nxt) > max_states:
                report(
                    "model-unsafe", s,
                    f"exploration exceeded {max_states} states — the model "
                    "scope is unbounded; tighten its constants",
                )
                return res
        frontier = nxt

    # reachability (revivability): the goal set must be reachable from
    # every reachable state.  Computed as a backward fixed point over the
    # forward edges re-derived per state (models are cheap).
    if hasattr(model, "is_goal") and not res.violations:
        can_reach = {s for s in seen if model.is_goal(s)}
        changed = True
        succs = {
            s: [s2 for _l, s2 in model.actions(s)]
            for s in seen
            if not model.is_terminal(s)
        }
        while changed:
            changed = False
            for s, outs in succs.items():
                if s not in can_reach and any(o in can_reach for o in outs):
                    can_reach.add(s)
                    changed = True
        dead = sorted(seen - can_reach, key=lambda s: res.trace(s))
        if dead:
            report(
                "model-unrevivable", dead[0],
                f"{len(dead)} reachable state(s) cannot reach the goal "
                "(recovery) set — a one-way door into permanent "
                "isolation",
            )
    return res


def default_models() -> List[object]:
    """The shipped scope: the acceptance-criterion 3-party/2-step session
    space (with a floor spread that exercises the max-join), the same
    space under the fault plane (one party may die at any instant — the
    abort-convergence property: no survivor is ever left stuck in the
    lockstep barrier), plus the full breaker machine."""
    return [
        SessionModel(n_parties=3, steps=2, floors=(0, 1, 3)),
        SessionModel(n_parties=3, steps=2, floors=(0, 1, 3), max_deaths=1),
        ResumeSessionModel(n_parties=3, steps=2),
        OverlapSessionModel(n_parties=3, steps=3, chunks=3),
        BreakerModel(),
    ]


def check(models: Optional[List[object]] = None) -> List[Violation]:
    out: List[Violation] = []
    for model in models if models is not None else default_models():
        out.extend(explore(model).violations)
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="fabricverify.modelcheck")
    ap.add_argument(
        "--parties", type=int, default=3,
        help="session model party count (default 3)",
    )
    ap.add_argument(
        "--steps", type=int, default=2,
        help="session model proposed step count (default 2)",
    )
    args = ap.parse_args(argv)
    floors = tuple(min(i * 2, args.steps + 1) for i in range(args.parties))
    models = [
        SessionModel(
            n_parties=args.parties, steps=args.steps, floors=floors
        ),
        SessionModel(
            n_parties=args.parties, steps=args.steps, floors=floors,
            max_deaths=1,
        ),
        # the resume scope's step-granular state space grows much faster
        # than the base model's; its exhaustive scope is pinned at 2
        # steps (≈430k states) regardless of --steps
        ResumeSessionModel(n_parties=args.parties, steps=2),
        # the overlap scope is chunk-granular — pinned at 3 parties /
        # 3 steps / 3 chunks (~177k states) for the same reason
        OverlapSessionModel(n_parties=3, steps=3, chunks=3),
        BreakerModel(),
    ]
    rc = 0
    for model in models:
        res = explore(model)
        status = "ok" if not res.violations else "FAIL"
        print(
            f"[{status}] {model.name}: {res.states} states, "
            f"{res.transitions} transitions explored"
        )
        for v in res.violations:
            print(f"  {v}")
            rc = 1
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
