"""fabricverify — lock-order, lifecycle, and state-machine verification
for the concurrency plane.

PR 6's fabriclint made the FFI boundary machine-checked; this sibling
does the same for the repo's *concurrency* discipline, which until now
was tested only on happy interleavings:

- **lockorder** (lockorder.py): every lock acquisition site
  (``with self._lock:``, ``.acquire()``, ``Condition`` construction)
  across ``incubator_brpc_tpu/`` is extracted into named lock entities;
  an intraprocedural call graph propagates "locks acquired while
  holding" edges into one global lock-ordering graph.  Cycles are
  violations (``lock-cycle``); the acyclic result is rendered as the
  documented lock hierarchy in docs/ANALYSIS.md.
- **lifecycle** (lifecycle.py): borrow/give_back balance for
  ``SimpleDataPool``, schedule/unschedule balance for ``TimerThread``
  ids, and registration/removal balance for callback hooks
  (``on_failed``/``on_revived`` appends, naming observers, scrape
  hooks).  The PR 3 ``on_revived`` leak and the PR 1 scrape-vs-stop
  UAF were both this class; the pass makes them structural errors.
- **modelcheck** (modelcheck.py + models.py): a small-scope exhaustive
  explorer (bounded parties/steps, message reorder + drop + duplicate)
  over extracted models of the mc_dispatch session protocol and the
  circuit-breaker state machine, asserting no stuck session, close
  convergence, and breaker revivability from every reachable state.

Exemptions use fabriclint's grammar — the SAME marker, the same
enforced-non-empty reason::

    # fabriclint: allow(<rule>) <why the rule does not apply here>

fabricverify's rule ids are registered in ``tools.fabriclint.RULES`` so
one annotation scanner serves both tools.  Run everything:
``python -m tools.fabricverify`` (or ``make lint``, which merges the
fabriclint and fabricverify exit codes); the model checker alone:
``make verify-models``.  The same checks run inside tier-1 via
tests/test_static_analysis.py.
"""

from __future__ import annotations

from typing import List

# Shared plumbing: one Violation type, one annotation grammar, one file
# walker.  fabricverify's rules live in fabriclint.RULES (see VERIFY_RULES
# there) so a single scan validates every allow() in the tree.
from tools.fabriclint import (  # noqa: F401  (re-exported surface)
    REPO_ROOT,
    Violation,
    allowed,
    iter_py_files,
    scan_annotations,
    to_records,
)

# The rule ids this tool owns — defined once, in fabriclint.VERIFY_RULES
# (where they register into the shared RULES grammar); re-exported here
# so --list-rules/--rule filtering can never drift from the scanner.
from tools.fabriclint import VERIFY_RULES as RULES  # noqa: E402


def run_all() -> List[Violation]:
    """Run all three passes; returns unexempted violations."""

    from tools.fabricverify import lifecycle, lockorder, modelcheck

    out: List[Violation] = []
    out.extend(lockorder.check())
    out.extend(lifecycle.check())
    out.extend(modelcheck.check())
    seen = set()
    unique: List[Violation] = []
    for v in out:
        key = (v.rule, v.path, v.line, v.message)
        if key not in seen:
            seen.add(key)
            unique.append(v)
    return unique
