#!/usr/bin/env python
"""rpc_replay — re-issue dumped requests against a server (reference
tools/rpc_replay: reads rpc_dump sample files and replays them through a
Channel at a chosen concurrency).

Usage:
    python tools/rpc_replay.py --dir ./rpc_dump --server 127.0.0.1:8000 \
        --threads 4 --times 1
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import threading


def load_requests(path_or_dir: str):
    """All (meta, payload, attachment) samples under a file or directory."""
    from incubator_brpc_tpu.rpc.dump import load_dump_file

    if os.path.isdir(path_or_dir):
        paths = sorted(glob.glob(os.path.join(path_or_dir, "requests.*")))
    else:
        paths = [path_or_dir]
    out = []
    for p in paths:
        out.extend(load_dump_file(p))
    return out


def run_replay(
    requests,
    server: str,
    threads: int = 1,
    times: int = 1,
    timeout_ms: float = 1000,
) -> dict:
    from incubator_brpc_tpu.rpc import Channel, ChannelOptions

    ch = Channel()
    if not ch.init(server, options=ChannelOptions(timeout_ms=timeout_ms)):
        raise SystemExit(f"cannot init channel to {server}")
    work = list(requests) * times
    counts = {"ok": 0, "fail": 0}
    lock = threading.Lock()
    idx = {"next": 0}

    def worker():
        ok = fail = 0
        while True:
            with lock:
                i = idx["next"]
                if i >= len(work):
                    break
                idx["next"] = i + 1
            meta, payload, attachment = work[i]
            cntl = ch.call_method(
                meta.service, meta.method, payload, attachment=attachment
            )
            if cntl.ok():
                ok += 1
            else:
                fail += 1
        with lock:
            counts["ok"] += ok
            counts["fail"] += fail

    ts = [threading.Thread(target=worker) for _ in range(max(1, threads))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return dict(counts, total=len(work))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", required=True, help="dump file or directory")
    p.add_argument("--server", required=True, help="ip:port or naming url")
    p.add_argument("--threads", type=int, default=1)
    p.add_argument("--times", type=int, default=1, help="replay each sample N times")
    p.add_argument("--timeout-ms", type=float, default=1000)
    args = p.parse_args(argv)

    requests = load_requests(args.dir)
    if not requests:
        print(f"no samples under {args.dir}", file=sys.stderr)
        return 1
    stats = run_replay(
        requests,
        args.server,
        threads=args.threads,
        times=args.times,
        timeout_ms=args.timeout_ms,
    )
    print(f"replayed={stats['total']} ok={stats['ok']} fail={stats['fail']}")
    return 0 if stats["fail"] == 0 else 1


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
