"""plane-parity — mechanical diff of the constants mirrored across the
C++ and Python planes.

The native plane's acceptance contract since PR 2 is byte-identity with
the Python implementation: same frame headers, same RpcMeta field
numbers, same codec ids, same error codes and ``berror`` texts, same
snappy parse constants, same ceilings.  Until now that contract was
guarded only by round-trip tests — a skewed constant showed up (at
best) as a byte-identity test flake three layers away.  This pass
extracts each mirrored surface FROM BOTH SOURCES mechanically and diffs
them at lint time:

- **PRPC framing**: ``kMagicPrpc``/``kPrpcHeader`` vs ``baidu_std.py``'s
  ``MAGIC``/``HEADER_BYTES``.
- **tbus framing**: magic, 32-byte header, the four wire flag bits vs
  ``tbus_std.py``.
- **RpcMeta field numbers**, both directions: the scanner's decode
  branches (``field == N`` classified by the ``m.<attr>`` they fill)
  and the packers' tag bytes (classified by the value they emit) vs the
  decode ``elif`` chain and ``_f_varint/_f_bytes/_tag`` calls in
  ``baidu_std.py``.
- **Codec enum**: ``kCompressSnappy/Gzip/Zlib1`` + ``codec_name`` vs
  ``_COMPRESS_TO_WIRE``.
- **Error codes and texts**: the ``ErrorCodes`` defaults vs
  ``utils/status.py`` ``ErrorCode``; ``kDeadlineShedText``/
  ``kUnauthorizedText`` vs ``berror``'s descriptions; the three
  decompress-reject texts vs the composed Python route text
  (``"decompress failed: " + <codec error>``).
- **Snappy constants**: hash multiplier, table size, skip schedule
  seed, shift seed vs ``snappy_codec.py``.
- **Flag defaults stamped into C++**: ``compress_min``/
  ``max_decompress`` initializers vs the ``native_compress_min_bytes``/
  ``max_decompress_bytes`` flag defaults.

A missing extraction anchor is itself a violation (``scan-parse``): if
either side is refactored out from under a regex, the pass screams
instead of silently comparing nothing.
"""

from __future__ import annotations

import os
import re
import struct
from typing import Dict, List, Optional, Tuple

from tools.fabriclint import REPO_ROOT, Violation, allowed, scan_annotations
from tools.fabricscan import cmodel

PKG = os.path.join(REPO_ROOT, "incubator_brpc_tpu")

PY_FILES = {
    "baidu_std": os.path.join(PKG, "protocol", "baidu_std.py"),
    "tbus_std": os.path.join(PKG, "protocol", "tbus_std.py"),
    "snappy": os.path.join(PKG, "protocol", "snappy_codec.py"),
    "compress": os.path.join(PKG, "protocol", "compress.py"),
    "status": os.path.join(PKG, "utils", "status.py"),
    "flags": os.path.join(PKG, "utils", "flags.py"),
    "server": os.path.join(PKG, "rpc", "server.py"),
    "native_plane": os.path.join(PKG, "transport", "native_plane.py"),
}


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


class _Joined:
    """Adjacent C++ string literals joined into one logical match."""

    def __init__(self, text: str, start: int):
        self._text = text
        self._start = start

    def group(self, _i: int = 1) -> str:
        return self._text

    def start(self, _i: int = 0) -> int:
        return self._start


class _Side:
    """One plane's source text + the extraction bookkeeping."""

    def __init__(self, path: str, text: str, out: List[Violation]):
        self.path = path
        self.text = text
        self.out = out

    def grab(self, pattern: str, what: str) -> Optional[re.Match]:
        m = re.search(pattern, self.text)
        if m is None:
            self.out.append(
                Violation(
                    "scan-parse", self.path, 1,
                    f"plane-parity anchor missing: {what} "
                    f"(pattern {pattern!r} found nothing — re-point the "
                    "extractor at the refactored code)",
                )
            )
        return m

    def int_at(self, pattern: str, what: str) -> Optional[Tuple[int, int]]:
        m = self.grab(pattern, what)
        if m is None:
            return None
        return int(m.group(1), 0), _line_of(self.text, m.start(1))


def _diff(out: List[Violation], what: str,
          cc: Optional[Tuple[object, int]], cc_path: str,
          py: Optional[Tuple[object, int]], py_path: str) -> None:
    if cc is None or py is None:
        return  # the missing anchor already screamed
    cval, cline = cc
    pval, _ = py
    if cval != pval:
        out.append(
            Violation(
                "plane-parity", cc_path, cline,
                f"{what}: C++ has {cval!r}, "
                f"{os.path.relpath(py_path, REPO_ROOT)} has {pval!r} — "
                "the twin implementations drifted",
            )
        )


# ---------------------------------------------------------------------------
# surface extractors
# ---------------------------------------------------------------------------


def _framing(out, cc: _Side, baidu: _Side, tbus: _Side) -> None:
    m = cc.int_at(r"kMagicPrpc = (0x[0-9A-Fa-f]+)", "PRPC magic")
    if m is not None:
        cc_magic = (struct.pack("<I", m[0]).decode("ascii"), m[1])
        p = baidu.grab(r'MAGIC = b"(\w+)"', "PRPC magic")
        if p is not None:
            _diff(out, "PRPC magic", cc_magic, cc.path,
                  (p.group(1), 0), baidu.path)
    _diff(out, "PRPC header bytes",
          cc.int_at(r"kPrpcHeader = (\d+)", "PRPC header size"), cc.path,
          baidu.int_at(r"HEADER_BYTES = (\d+)", "PRPC header size"),
          baidu.path)
    _diff(out, "tbus magic",
          cc.int_at(r"\bkMagic = (0x[0-9A-Fa-f]+)", "tbus magic"), cc.path,
          tbus.int_at(r"\bMAGIC = (0x[0-9A-Fa-f]+)", "tbus magic"),
          tbus.path)
    _diff(out, "tbus header bytes",
          cc.int_at(r"\bkHeader = (\d+)", "tbus header size"), cc.path,
          tbus.int_at(r"\bHEADER_BYTES = (\d+)", "tbus header size"),
          tbus.path)
    for cname, pname in (
        ("kFlagResponse", "FLAG_RESPONSE"), ("kFlagStream", "FLAG_STREAM"),
        ("kFlagHasMeta", "FLAG_HAS_META"), ("kFlagBodyCrc", "FLAG_BODY_CRC"),
    ):
        _diff(out, f"tbus flag {pname}",
              cc.int_at(rf"{cname} = (\d+)", cname), cc.path,
              tbus.int_at(rf"{pname} = (\d+)", pname), tbus.path)


# semantic -> the attribute the C++ scanner fills / the Python decode sets
_CC_DECODE_ATTRS = {
    "request submessage": r"m\.req_sub\b",
    "response submessage": r"m\.is_response",
    "compress_type": r"m\.compress\b",
    "correlation_id": r"m\.cid\b",
    "attachment_size": r"m\.attachment\b",
    "authentication_data": r"m\.auth\b",
}
_CC_SUB_ATTRS = {
    "service_name": r"m\.svc\b",
    "method_name": r"m\.mth\b",
    "timeout_ms": r"m\.timeout_ms\b",
    "error_code": r"m\.error_code\b",
    # Dapper trace context (decode side): the cutter's fast-path fields
    "log_id": r"m\.log_id\b",
    "trace_id": r"m\.trace_id\b",
    "span_id": r"m\.span_id\b",
    "parent_span_id": r"m\.parent_span_id\b",
    "traced_sampled": r"m\.sampled\b",
}
_PY_DECODE_ATTRS = {
    "compress_type": r"m\.compress_type = ",
    "correlation_id": r"m\.correlation_id = ",
    "attachment_size": r"m\.attachment_size = ",
    "authentication_data": r"m\.authentication_data = ",
}
_PY_SUB_ATTRS = {
    "service_name": r"m\.service_name = ",
    "method_name": r"m\.method_name = ",
    "timeout_ms": r"m\.timeout_ms = ",
    "error_code": r"m\.error_code = ",
    "log_id": r"m\.log_id = ",
    "trace_id": r"m\.trace_id = ",
    "span_id": r"m\.span_id = ",
    "parent_span_id": r"m\.parent_span_id = ",
    "traced_sampled": r"m\.sampled = ",
}


def _classify_branches(side: _Side, branch_re: str,
                       attrs: Dict[str, str],
                       window: int) -> Dict[str, Tuple[int, int]]:
    """{semantic: (field_no, line)} — each `<var> == N` branch classified
    by the first known attribute assigned in its window."""

    found: Dict[str, Tuple[int, int]] = {}
    for m in re.finditer(branch_re, side.text):
        ctx = side.text[m.end(): m.end() + window]
        best = None
        for sem, attr_re in attrs.items():
            am = re.search(attr_re, ctx)
            if am and (best is None or am.start() < best[1]):
                best = (sem, am.start())
        if best and best[0] not in found:
            found[best[0]] = (int(m.group(1)), _line_of(side.text, m.start()))
    return found


def _rpc_meta_decode(out, cc: _Side, baidu: _Side) -> None:
    cc_map = _classify_branches(cc, r"\bfield == (\d+)\b",
                                _CC_DECODE_ATTRS, 260)
    cc_map.update(_classify_branches(cc, r"\bf2 == (\d+)\b",
                                     _CC_SUB_ATTRS, 200))
    py_map = _classify_branches(baidu, r"field_no == (\d+)\b",
                                _PY_DECODE_ATTRS, 120)
    py_map.update(_classify_branches(baidu, r"\bf2 == (\d+)\b",
                                     _PY_SUB_ATTRS, 120))
    # the submessage routing fields come from the tag-structured branches
    pm = baidu.grab(r"if field_no == (\d+) and wt == 2:\s*\n\s*for f2",
                    "RpcMeta request-submessage decode branch")
    if pm:
        py_map["request submessage"] = (
            int(pm.group(1)), _line_of(baidu.text, pm.start()))
    pm = baidu.grab(
        r"elif field_no == (\d+) and wt == 2:\s*\n\s*m\.is_response",
        "RpcMeta response-submessage decode branch")
    if pm:
        py_map["response submessage"] = (
            int(pm.group(1)), _line_of(baidu.text, pm.start()))
    for sem in sorted(set(_CC_DECODE_ATTRS) | set(_CC_SUB_ATTRS)):
        if sem not in cc_map:
            out.append(Violation(
                "scan-parse", cc.path, 1,
                f"plane-parity: no decode branch found for {sem} in the "
                "C++ meta scanners"))
            continue
        if sem not in py_map:
            out.append(Violation(
                "scan-parse", baidu.path, 1,
                f"plane-parity: no decode branch found for {sem} in "
                "baidu_std.py"))
            continue
        _diff(out, f"RpcMeta decode field number of {sem}",
              cc_map[sem], cc.path, py_map[sem], baidu.path)


# pack-side: tag byte classified by the value emitted right after it
_CC_PACK_CTX = {
    "request submessage": r"put_varint\(tmp, sub_len\)",
    "compress_type": r"put_varint\(tmp, compress\)",
    "correlation_id": r"put_varint\(tmp, cid\)",
    "attachment_size": r"put_varint\(tmp, att_len\)",
    "authentication_data": r"put_varint\(tmp, auth_len\)",
}
_CC_RESP_CTX = {
    "response submessage": r"put_varint\(meta \+ mn, sn\)",
    "error_code": r"put_varint\(sub \+ sn, error_code\)",
    "error_text": r"put_varint\(sub \+ sn, text_len\)",
}
_CC_PUMP_CTX = {
    "request submessage": r"put_varint\(t \+ o, meta_len\)",
    "compress_type": r"put_varint\(t \+ o, compress\)",
    "correlation_id": r"cid_off = o",
    "authentication_data": r"put_varint\(t \+ o, auth_len\)",
}
# the TRACED pump template's RpcRequestMeta trace tags (decode twin:
# the scanner's f2 branches; pack twin: encode_request_submeta)
_CC_PUMP_TRACE_CTX = {
    "log_id": r"put_varint\(t \+ o, ch->tr_log_id\)",
    "trace_id": r"put_varint\(t \+ o, ch->tr_trace_id\)",
    "span_id": r"tspan_off = o",
    "parent_span_id": r"put_varint\(t \+ o, ch->tr_parent_span_id\)",
    "traced_sampled": r"RpcRequestMeta\.traced_sampled",
}
_PUMP_TRACE_PY = {
    "log_id": r"_f_varint\((\d+), log_id\)",
    "trace_id": r"_f_varint\((\d+), trace_id\)",
    "span_id": r"_f_varint\((\d+), span_id\)",
    "parent_span_id": r"_f_varint\((\d+), parent_span_id\)",
    "traced_sampled": r"_f_varint\((\d+), 1 if sampled else 0\)",
}


def _cc_pack_tags(side: _Side, ctxmap: Dict[str, str],
                  where: str) -> Dict[str, Tuple[int, int]]:
    found: Dict[str, Tuple[int, int]] = {}
    for m in re.finditer(
        r"(?:push_back\(|\[\w+\+\+\] = )(0x[0-9A-Fa-f]{2})\)?;", side.text
    ):
        ctx = side.text[m.end(): m.end() + 160]
        # the NEAREST context wins: a tag's window may run into the next
        # tag's emit call
        best = None
        for sem, ctx_re in ctxmap.items():
            cm = re.search(ctx_re, ctx)
            if cm and (best is None or cm.start() < best[1]):
                best = (sem, cm.start())
        if best and best[0] not in found:
            tag = int(m.group(1), 16)
            found[best[0]] = (tag >> 3, _line_of(side.text, m.start(1)))
    for sem in ctxmap:
        if sem not in found:
            side.out.append(Violation(
                "scan-parse", side.path, 1,
                f"plane-parity: no pack tag found for {sem} in {where}"))
    return found


def _rpc_meta_pack(out, cc: _Side, baidu: _Side) -> None:
    py: Dict[str, Tuple[int, int]] = {}

    def py_field(pattern: str, sem: str) -> None:
        m = baidu.grab(pattern, f"{sem} encode call")
        if m:
            py[sem] = (int(m.group(1)), _line_of(baidu.text, m.start()))

    py_field(r"_tag\((\d+), 2\) \+ _varint\(len\(sub\)\) \+ sub\n"
             r"\s*else:", "response submessage")
    # request tag: the non-response arm
    m = baidu.grab(
        r"else:\s*\n\s*sub = encode_request_submeta\((?:.|\n)*?"
        r"_tag\((\d+), 2\)", "request submessage encode")
    if m:
        py["request submessage"] = (int(m.group(1)),
                                    _line_of(baidu.text, m.start(1)))
    py_field(r"_f_varint\((\d+), self\.compress_type\)", "compress_type")
    py_field(r"_f_varint\((\d+), self\.correlation_id\)", "correlation_id")
    py_field(r"_f_varint\((\d+), self\.attachment_size\)", "attachment_size")
    py_field(r"_f_bytes\((\d+), self\.authentication_data\)",
             "authentication_data")
    py_field(r"_f_varint\((\d+), self\.error_code\)", "error_code")
    py_field(r"_f_bytes\(\s*(\d+), self\.error_text", "error_text")

    req = _cc_pack_tags(cc, _CC_PACK_CTX, "pack_prpc_request")
    resp = _cc_pack_tags(cc, _CC_RESP_CTX, "append_prpc_resp_header")
    pump = _cc_pack_tags(cc, _CC_PUMP_CTX, "tb_channel_pump's template")
    for sem, ccv in {**req, **resp}.items():
        if sem in py:
            _diff(out, f"RpcMeta pack field number of {sem}",
                  ccv, cc.path, py[sem], baidu.path)
    for sem, ccv in pump.items():
        if sem in py:
            _diff(out, f"RpcMeta pump-template field number of {sem}",
                  ccv, cc.path, py[sem], baidu.path)
    # submeta twins (service/method/timeout/trace context) ride
    # encode_request_submeta: the PACK side of every RpcRequestMeta
    # field is diffed against the C++ scanner's DECODE branch for the
    # same semantic — a client stamping trace_id into field N that the
    # cutter decodes from field M is exactly the drift this pins
    cm = _classify_branches(cc, r"\bf2 == (\d+)\b", _CC_SUB_ATTRS, 200)
    for pat, sem in (
        (r"_f_bytes\((\d+), service\.encode\(\)\)", "service_name"),
        (r"_f_bytes\((\d+), method\.encode\(\)\)", "method_name"),
        (r"_f_varint\((\d+), timeout_ms\)", "timeout_ms"),
        (r"_f_varint\((\d+), log_id\)", "log_id"),
        (r"_f_varint\((\d+), trace_id\)", "trace_id"),
        (r"_f_varint\((\d+), span_id\)", "span_id"),
        (r"_f_varint\((\d+), parent_span_id\)", "parent_span_id"),
        (r"_f_varint\((\d+), 1 if sampled else 0\)", "traced_sampled"),
    ):
        m = baidu.grab(pat, f"submeta {sem}")
        if m and sem in cm:
            _diff(out, f"RpcRequestMeta field number of {sem}",
                  cm[sem], cc.path,
                  (int(m.group(1)), 0), baidu.path)
    # the traced pump template packs the same fields natively: its tag
    # bytes (classified by the emit call that follows each) must agree
    # with encode_request_submeta's field numbers too
    pump_trace = _cc_pack_tags(cc, _CC_PUMP_TRACE_CTX,
                               "tb_channel_pump's traced template")
    for sem, ccv in pump_trace.items():
        m = baidu.grab(_PUMP_TRACE_PY[sem], f"submeta {sem}")
        if m:
            _diff(out, f"traced pump-template field number of {sem}",
                  ccv, cc.path, (int(m.group(1)), 0), baidu.path)


def _codec_enum(out, cc: _Side, baidu: _Side) -> None:
    names = {}
    for m in re.finditer(
        r"case (kCompress\w+): return \"(\w+)\";", cc.text
    ):
        names[m.group(1)] = m.group(2)
    cc_map: Dict[str, Tuple[int, int]] = {}
    for cname, wire_name in names.items():
        v = cc.int_at(rf"{cname} = (\d+)", cname)
        if v is not None:
            cc_map[wire_name] = v
    if not cc_map:
        cc.grab(r"case kCompressNothing", "codec_name mapping")  # scream
    pm = baidu.grab(r"_COMPRESS_TO_WIRE = \{([^}]*)\}",
                    "codec wire-id table")
    if pm is None:
        return
    py_map = {
        k: int(v)
        for k, v in re.findall(r'"(\w*)": (\d+)', pm.group(1))
    }
    pline = _line_of(baidu.text, pm.start())
    for name, ccv in sorted(cc_map.items()):
        if name not in py_map:
            out.append(Violation(
                "plane-parity", cc.path, ccv[1],
                f"codec {name!r} (wire id {ccv[0]}) has no entry in "
                "baidu_std._COMPRESS_TO_WIRE"))
            continue
        _diff(out, f"codec wire id of {name!r}", ccv, cc.path,
              (py_map[name], pline), baidu.path)


_CC_ERRS = {
    "enomethod": "ENOMETHOD", "elimit": "ELIMIT", "erequest": "EREQUEST",
    "edeadline": "EDEADLINE", "erpcauth": "ERPCAUTH",
}


def _error_surface(out, cc: _Side, status: _Side) -> None:
    for cfield, pname in _CC_ERRS.items():
        _diff(out, f"error code {pname}",
              cc.int_at(rf"\b{cfield} = (\d+);", f"ErrorCodes.{cfield}"),
              cc.path,
              status.int_at(rf"\b{pname} = (\d+)", f"ErrorCode.{pname}"),
              status.path)
    for cname, pname in (
        ("kDeadlineShedText", "EDEADLINE"),
        ("kUnauthorizedText", "ERPCAUTH"),
    ):
        cm = cc.grab(rf'{cname}\[\] = "([^"]*)"', cname)
        pm = status.grab(
            rf'ErrorCode\.{pname}: "([^"]*)"', f"berror({pname}) text"
        )
        if cm and pm:
            _diff(out, f"berror({pname}) text",
                  (cm.group(1), _line_of(cc.text, cm.start())), cc.path,
                  (pm.group(1), 0), status.path)


def _decompress_texts(out, cc: _Side, compress: _Side, snappy: _Side,
                      server: _Side, baidu: _Side) -> None:
    sm = server.grab(r'f"decompress failed: \{e\}"',
                     "server decompress-reject prefix")
    prefix = "decompress failed: " if sm else None
    if prefix is None:
        return

    def norm_cc(fmt: str) -> str:
        return fmt.replace("%u", "{}").replace("%zu", "{}")

    # unknown codec: compress.py text + baidu_std's wire-N surfacing
    cm = cc.grab(r'"(decompress failed: unknown compression codec [^"]*)"',
                 "unknown-codec reject text")
    pm = compress.grab(r'f"unknown compression codec \{name!r\}"',
                       "unknown-codec text")
    wm = baidu.grab(r'f"wire-\{rm\.compress_type\}"',
                    "out-of-enum codec name surfacing")
    if cm and pm and wm:
        py_text = prefix + "unknown compression codec 'wire-{}'"
        _diff(out, "unknown-codec reject text",
              (norm_cc(cm.group(1)), _line_of(cc.text, cm.start())),
              cc.path, (py_text, 0), compress.path)
    # ceiling text (one template shared by the zlib loop and snappy;
    # the C++ literal is split across adjacent string fragments)
    cm = cc.grab(
        r'"(decompress failed: decompressed size exceeds [^"]*)"'
        r'((?:\s*"[^"]*")*)',
        "decompress-ceiling reject text")
    if cm is not None:
        joined = cm.group(1) + "".join(
            re.findall(r'"([^"]*)"', cm.group(2)))
        cm = _Joined(joined, cm.start())
    pm = compress.grab(
        r'f"decompressed size exceeds max_decompress_bytes \(\{\w+\}\)"',
        "ceiling text (compress.py)")
    sm2 = snappy.grab(
        r'f"decompressed size exceeds max_decompress_bytes \(\{\w+\}\)"',
        "ceiling text (snappy_codec.py)")
    if cm and pm and sm2:
        py_text = prefix + "decompressed size exceeds " \
            "max_decompress_bytes ({})"
        _diff(out, "decompress-ceiling reject text",
              (norm_cc(cm.group(1)), _line_of(cc.text, cm.start())),
              cc.path, (py_text, 0), compress.path)
    # corrupt-body text, instantiated for snappy on both sides
    cm = cc.grab(r'"(decompress failed: corrupt %s body)"',
                 "corrupt-body reject text")
    pm = compress.grab(r'"corrupt snappy body"', "corrupt-snappy text")
    if cm and pm:
        _diff(out, "corrupt-body reject text (snappy)",
              (cm.group(1).replace("%s", "snappy"),
               _line_of(cc.text, cm.start())),
              cc.path, (prefix + "corrupt snappy body", 0), compress.path)


def _snappy_constants(out, cc: _Side, snappy: _Side) -> None:
    _diff(out, "snappy hash multiplier",
          cc.int_at(r"load32le\(data \+ i\) \* (0x[0-9A-Fa-f]+)u",
                    "snappy hash multiplier"),
          cc.path,
          snappy.int_at(r"_HASH_MUL = (0x[0-9A-Fa-f]+)",
                        "snappy hash multiplier"),
          snappy.path)
    cm = cc.grab(r"constexpr uint32_t kSnappyTableBits = (\d+);",
                 "snappy table size")
    pm = snappy.int_at(r"_MAX_TABLE = 1 << (\d+)", "snappy table size")
    if cm and pm:
        _diff(out, "snappy table size (log2)",
              (int(cm.group(1)), _line_of(cc.text, cm.start())), cc.path,
              pm, snappy.path)
    _diff(out, "snappy skip-schedule seed",
          cc.int_at(r"uint32_t skip = (\d+);", "snappy skip seed"), cc.path,
          snappy.int_at(r"\n    skip = (\d+)\n", "snappy skip seed"),
          snappy.path)
    _diff(out, "snappy shift seed",
          cc.int_at(r"int shift = (\d+);\s*// 32 - log2",
                    "snappy shift seed"), cc.path,
          snappy.int_at(r"\n    shift = (\d+)", "snappy shift seed"),
          snappy.path)


def _telemetry_record(out, cc: _Side, nplane: _Side) -> None:
    """The telemetry record ABI size, anchored on BOTH planes: the
    static_assert in tbnet.cc vs native_plane.py's
    ``_TELEMETRY_RECORD_BYTES`` (which the drain dtype asserts against
    at runtime).  fabriclint's ffi-struct pass checks the field-level
    layout three ways; this is the textual tripwire that a grown record
    cannot ship with one side's size constant left behind."""
    _diff(out, "telemetry record ABI bytes",
          cc.int_at(
              r"static_assert\(sizeof\(tb_telemetry_record\) == (\d+)",
              "telemetry record static_assert"), cc.path,
          nplane.int_at(r"_TELEMETRY_RECORD_BYTES = (\d+)",
                        "telemetry record size constant"), nplane.path)


def _int_expr(s: str) -> Optional[int]:
    s = s.strip().rstrip(",")
    if not re.fullmatch(r"[\d\s*+<u()]+", s):
        return None
    return int(eval(s.replace("u", "")))  # arithmetic-only by the regex


def _flag_defaults(out, cc: _Side, flags: _Side) -> None:
    for cc_pat, flag, what in (
        (r"size_t compress_min = ([^;]+);", "native_compress_min_bytes",
         "response-compression floor default"),
        (r"size_t max_decompress = ([^;]+);", "max_decompress_bytes",
         "decompress-ceiling default"),
    ):
        cm = cc.grab(cc_pat, what)
        pm = flags.grab(
            rf'define_flag\(\s*"{flag}",\s*([^,]+),', f"{flag} default"
        )
        if not (cm and pm):
            continue
        ccv = _int_expr(cm.group(1))
        pyv = _int_expr(pm.group(1))
        if ccv is None or pyv is None:
            out.append(Violation(
                "scan-parse", cc.path, _line_of(cc.text, cm.start()),
                f"plane-parity: could not evaluate {what} initializers "
                f"({cm.group(1)!r} vs {pm.group(1)!r})"))
            continue
        _diff(out, what,
              (ccv, _line_of(cc.text, cm.start())), cc.path,
              (pyv, 0), flags.path)


# ---------------------------------------------------------------------------


def check(tbnet_text: Optional[str] = None,
          overrides: Optional[Dict[str, str]] = None) -> List[Violation]:
    overrides = overrides or {}
    out: List[Violation] = []

    if tbnet_text is None:
        with open(cmodel.TBNET_CC) as fh:
            tbnet_text = fh.read()
    cc = _Side(cmodel.TBNET_CC, tbnet_text, out)

    sides: Dict[str, _Side] = {}
    for key, path in PY_FILES.items():
        text = overrides.get(key)
        if text is None:
            with open(path) as fh:
                text = fh.read()
        sides[key] = _Side(path, text, out)

    _framing(out, cc, sides["baidu_std"], sides["tbus_std"])
    _rpc_meta_decode(out, cc, sides["baidu_std"])
    _rpc_meta_pack(out, cc, sides["baidu_std"])
    _codec_enum(out, cc, sides["baidu_std"])
    _error_surface(out, cc, sides["status"])
    _decompress_texts(out, cc, sides["compress"], sides["snappy"],
                      sides["server"], sides["baidu_std"])
    _snappy_constants(out, cc, sides["snappy"])
    _flag_defaults(out, cc, sides["flags"])
    _telemetry_record(out, cc, sides["native_plane"])

    # exemptions are looked up in the file each violation is anchored in
    # (a C++ drift in tbnet.cc, a missing-anchor scream in the Python
    # twin) — an allow() only silences violations in its own file
    texts = {cmodel.TBNET_CC: tbnet_text}
    for key, path in PY_FILES.items():
        texts[path] = sides[key].text
    anns = {p: scan_annotations(p, t) for p, t in texts.items()}
    return [
        v for v in out
        if v.path not in anns or not allowed(anns[v.path], v.rule, v.line)
    ]
