"""ownership — reactor-ownership checking for the multi-reactor plane.

PR 9's headline claim — "zero cross-reactor locks on the
cut→decode→dispatch→pack path" — rests on a threading discipline that
lived only in comments: every mutable field of the native plane's
shared structures is owned by exactly one thread context, and foreign
contexts reach it only through atomics, a lock, or the telemetry ring's
sequence protocol.  This pass makes the discipline declared and
checked:

**Owners** (``// fabricscan: owner(...)`` on the field or global)

- ``loop``    — the reactor loop thread that owns the enclosing
  instance (NetConn fields, the reactor's ZCtx/scratch, …).  Accesses
  are legal from loop-role code, from init (before the threads exist)
  and from stop (after they joined).
- ``worker``  — dispatch-pool worker context (WorkTask fields after the
  publication handoff).  Same init/stop latitude.
- ``shared``  — any thread, but every access must be visibly justified:
  the function is marked ``// fabricscan: locked`` (its callers hold
  the guarding mutex), or a lock acquisition appears in the function
  before the access, or an acquire-load of an atomic appears before it
  (the ring's per-cell seq protocol).
- ``init``    — written only during single-threaded setup (construction
  sites, ``role(init)`` functions = the pre-listen/pre-connect
  configuration surface), read-only afterwards from anywhere.

Fields that are ``std::atomic``, sync primitives (mutex/cv/thread),
``const``, or themselves checked-struct values (ownership lives on the
inner fields) need no annotation.  Everything else mutable on a checked
struct without an owner is an ``owner-missing`` violation — unannotated
shared mutable state is the bug class this pass exists for.

**Roles** propagate over the call graph: seeds come from
``// fabricscan: role(...)`` (``loop_run`` is the loop thread,
``pool_worker`` the worker, the pre-listen setters are ``init``, the
teardown entry points ``stop``) and every un-seeded ``extern "C"``
``tb_*`` export defaults to ``python`` (an arbitrary interpreter
thread).  A seeded function keeps ONLY its seed — a thread entry point
does not inherit the role of the code that spawned it — while unseeded
functions take the union of their callers' roles.

Accesses are found by typing each function's parameters and locals
against the checked structs and walking member chains
(``c->loop->batch``): per-instance ownership falls out of the chain —
reaching a reactor's ZCtx from python role goes through the loop-owned
pointer and is flagged there, while a worker's stack-local ZCtx is a
fresh instance and exempt.  ``// fabricscan: borrows(Type)`` on a
function moves the obligation to its call sites (the codec helpers run
on whichever instance the caller hands them).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from tools.fabriclint import Violation, allowed, scan_annotations
from tools.fabricscan import cmodel
from tools.fabricscan.cmodel import CppFunc, Model

OWNERS = ("loop", "worker", "shared", "init")
ROLES = ("loop", "worker", "python", "init", "stop")

# structures reachable from more than one thread (or pinned to one, which
# is exactly the claim being checked).  Value-only scratch types (ReqCtx,
# PrpcMeta, MetaLite, Scan) never escape a stack frame and stay out.
CHECKED_STRUCTS = (
    "PollObj", "Wake", "Listener", "NetConn", "NetLoop", "NativeMethod",
    "tb_server", "TelemetryRing", "TelemetryCell", "ZCtx", "SnappyTable",
    "WorkDeque", "WorkTask", "DispatchPool", "tb_channel", "Pending",
    "tb_wsq",
)

# owner -> roles whose access needs no further justification
_FREE_ROLES = {
    "loop": {"loop", "init", "stop"},
    "worker": {"worker", "init", "stop"},
}

_MUTATORS = (
    "assign", "clear", "push_back", "pop_back", "emplace_back", "resize",
    "reserve", "insert", "erase",
)

_LOCKY_RE = re.compile(
    r"lock_guard\s*<|unique_lock\s*<|\.\s*lock\s*\(|try_lock\s*\(|"
    r"memory_order_acquire"
)


@dataclass
class _Access:
    struct: str
    fld: str
    pos: int       # offset of the member name in fn.body
    is_write: bool


def _struct_of_type(type_text: str) -> Optional[str]:
    for s in CHECKED_STRUCTS:
        if re.search(rf"\b{s}\b", type_text):
            return s
    return None


def field_needs_owner(f) -> bool:
    """Mutable plain state needs a declared owner; atomics, sync
    primitives, consts, and checked-struct-valued members (ownership
    lives on the inner fields) do not."""

    if f.is_atomic or f.is_sync or f.is_const:
        return False
    if _struct_of_type(f.type_text) and "*" not in f.type_text:
        return False  # embedded checked struct: inner fields carry owners
    return True


# ---------------------------------------------------------------------------
# role propagation
# ---------------------------------------------------------------------------


def seed_and_propagate(model: Model) -> List[Violation]:
    out: List[Violation] = []
    for fn in model.funcs.values():
        for r in fn.seeded_roles:
            if r not in ROLES:
                out.append(
                    Violation(
                        "scan-parse", model.path, fn.line,
                        f"{fn.qname}: role({r}) is not one of "
                        f"{'/'.join(ROLES)}",
                    )
                )
        fn.roles = set(fn.seeded_roles)
        # the C API surface: python threads, unless the seed says the
        # call is part of single-threaded setup/teardown
        if not fn.roles and fn.struct is None and fn.name.startswith("tb_"):
            fn.roles = {"python"}
    changed = True
    while changed:
        changed = False
        for fn in model.funcs.values():
            for callee_q in fn.calls:
                callee = model.funcs[callee_q]
                if callee.seeded_roles:
                    continue  # thread entries keep their seed only
                add = fn.roles - callee.roles
                if add:
                    callee.roles |= add
                    changed = True
    return out


# ---------------------------------------------------------------------------
# access extraction
# ---------------------------------------------------------------------------

_DECL_RE_TMPL = (
    r"(?:^|[;{{}}()]|\bconst\b)\s*(?:static\s+thread_local\s+|"
    r"static\s+|thread_local\s+)*"
    r"(?P<type>{structs})\s*(?P<ref>[*&]*)\s+(?P<name>\w+)\s*(?P<init>=|;|\{{|:)"
)


def _local_env(fn: CppFunc) -> Tuple[Dict[str, str], Set[str]]:
    """(var -> struct) for typed locals/params, plus the EXEMPT set:
    value locals (fresh instances) and news (construction context)."""

    env: Dict[str, str] = {}
    exempt: Set[str] = set()
    for ptype, pname in fn.params:
        s = _struct_of_type(ptype)
        if s and pname:
            env[pname] = s
    decl_re = re.compile(
        _DECL_RE_TMPL.format(structs="|".join(CHECKED_STRUCTS))
    )
    for m in decl_re.finditer(fn.body):
        s, name = m.group("type"), m.group("name")
        env[name] = s
        if "*" not in m.group("ref") and "&" not in m.group("ref"):
            exempt.add(name)  # fresh value instance on this frame
    # construction context: `X = new S(...)` exempts accesses through X
    # (the object is unpublished while this function fills it in)
    for m in re.finditer(
        rf"([\w.>\-]+)\s*=\s*new\s+(?:{'|'.join(CHECKED_STRUCTS)})\b",
        fn.body,
    ):
        exempt.add(m.group(1).replace("->", "."))
    return env, exempt


_CHAIN_RE_TMPL = r"\b{var}\s*((?:(?:->|\.)\s*\w+\s*(?:\[[^\]]*\])?)+)"
_MEMBER_RE = re.compile(r"(?:->|\.)\s*(\w+)")


def _is_write(body: str, end: int) -> bool:
    tail = body[end: end + 60]
    tail = re.sub(r"^\s*(?:\[[^\]]*\]\s*)*", "", tail)  # skip subscripts
    if re.match(r"(?:\+\+|--|(?:<<|>>|[+\-*/|&^%])?=(?!=))", tail):
        return True
    m = re.match(r"\.\s*(\w+)\s*\(", tail)
    if m and m.group(1) in _MUTATORS:
        return True
    return False


def _accesses(fn: CppFunc, model: Model) -> List[_Access]:
    env, exempt = _local_env(fn)
    body = fn.body
    out: List[_Access] = []

    def walk(root_struct: str, chain_text: str, base_pos: int,
             root_exempt: bool) -> None:
        cur: Optional[str] = root_struct
        for m in _MEMBER_RE.finditer(chain_text):
            if cur is None:
                break
            member = m.group(1)
            f = model.structs.get(cur, {}).get(member)
            if f is None:
                break  # a method call or an unmodeled member: chain ends
            if not root_exempt and cur in CHECKED_STRUCTS:
                out.append(
                    _Access(cur, member, base_pos + m.start(1),
                            _is_write(body, base_pos + m.end(1)))
                )
            cur = _struct_of_type(f.type_text)

    for var, s in env.items():
        var_exempt = var in exempt
        for m in re.finditer(_CHAIN_RE_TMPL.format(var=re.escape(var)),
                             body):
            walk(s, m.group(1), m.start(1), var_exempt)
    # construction-exempt chains spelled as chains (`s->pool = new ...;
    # s->pool->workers...`): re-run suppression by prefix
    # (handled below in check_function by position filtering)
    # bare this-members inside methods of checked structs
    if fn.struct in CHECKED_STRUCTS and not fn.is_ctor:
        fields = model.structs.get(fn.struct, {})
        for name, f in fields.items():
            for m in re.finditer(rf"(?<![\w.>])\b{name}\b(?!\s*\()", body):
                # skip if actually a chained member (preceded by -> or .)
                pre = body[max(0, m.start() - 2): m.start()]
                if pre.endswith(("->", ".")):
                    continue
                out.append(
                    _Access(fn.struct, name, m.start(),
                            _is_write(body, m.end()))
                )
    return out


def _chain_exempt_prefixes(fn: CppFunc) -> List[str]:
    """Textual prefixes (as they appear in the body) whose accesses are
    construction-time: `<prefix> = new <CheckedStruct>`."""

    out = []
    for m in re.finditer(
        rf"([\w.>\-]+(?:->|\.)[\w.>\-]+|\w+)\s*=\s*new\s+"
        rf"(?:{'|'.join(CHECKED_STRUCTS)})\b",
        fn.body,
    ):
        out.append(m.group(1))
    return out


# ---------------------------------------------------------------------------
# the check
# ---------------------------------------------------------------------------


def _lock_positions(fn: CppFunc) -> List[int]:
    return [m.start() for m in _LOCKY_RE.finditer(fn.body)]


def check_function(fn: CppFunc, model: Model) -> List[Violation]:
    if fn.is_ctor:
        return []
    out: List[Violation] = []
    locks = _lock_positions(fn)
    chain_exempt = _chain_exempt_prefixes(fn)
    seen: Set[Tuple[str, str, int]] = set()
    for acc in _accesses(fn, model):
        if acc.struct in fn.borrows:
            continue
        f = model.structs[acc.struct][acc.fld]
        if not field_needs_owner(f):
            continue
        owner = f.owner
        if owner is None:
            continue  # owner-missing reported once, at the field
        # construction-exempt prefix?
        stmt_start = fn.body.rfind(";", 0, acc.pos) + 1
        region = fn.body[stmt_start: acc.pos + len(acc.fld) + 4]
        if any(p in region for p in chain_exempt):
            continue
        line = cmodel.line_of(fn, acc.pos)
        key = (acc.struct, acc.fld, line)
        if key in seen:
            continue
        ok = False
        why = ""
        if owner in _FREE_ROLES:
            bad = fn.roles - _FREE_ROLES[owner]
            ok = fn.roles and not bad
            why = (
                f"{acc.struct}.{acc.fld} is {owner}-owned but "
                f"{fn.qname} runs in role(s) "
                f"{','.join(sorted(bad)) or '?'} — use an atomic, the "
                "ring, or a lock"
            )
            if not fn.roles:
                why = (
                    f"{fn.qname} touches {owner}-owned "
                    f"{acc.struct}.{acc.fld} but has no derivable role — "
                    "seed it with // fabricscan: role(...)"
                )
        elif owner == "shared":
            ok = fn.locked or any(p < acc.pos for p in locks)
            why = (
                f"{acc.struct}.{acc.fld} is shared but {fn.qname} "
                "reaches it with no lock acquisition, acquire-load, or "
                "locked marker before the access"
            )
        elif owner == "init":
            ok = (not acc.is_write) or (
                fn.roles and fn.roles <= {"init", "stop"}
            )
            why = (
                f"{acc.struct}.{acc.fld} is init-owned (write-once "
                f"setup) but {fn.qname} writes it from role(s) "
                f"{','.join(sorted(fn.roles)) or '?'}"
            )
        else:
            ok = False
            why = (
                f"{acc.struct}.{acc.fld}: unknown owner {owner!r} "
                f"(expected {'/'.join(OWNERS)})"
            )
        if not ok:
            seen.add(key)
            out.append(Violation("ownership", model.path, line, why))
    return out


def check(tbnet_text: Optional[str] = None) -> List[Violation]:
    model = cmodel.parse_file(cmodel.TBNET_CC, text=tbnet_text)
    out: List[Violation] = []
    ann = scan_annotations(cmodel.TBNET_CC, tbnet_text)
    out.extend(seed_and_propagate(model))

    # unannotated mutable state on checked structs / globals
    for sname in CHECKED_STRUCTS:
        for f in model.structs.get(sname, {}).values():
            if field_needs_owner(f) and f.owner is None:
                out.append(
                    Violation(
                        "owner-missing", model.path, f.line,
                        f"{sname}.{f.name} ({f.type_text}) is mutable "
                        "shared state with no declared owner — add "
                        "// fabricscan: owner(loop|worker|shared|init)",
                    )
                )
            elif f.owner is not None and f.owner not in OWNERS:
                out.append(
                    Violation(
                        "scan-parse", model.path, f.line,
                        f"{sname}.{f.name}: owner({f.owner}) is not one "
                        f"of {'/'.join(OWNERS)}",
                    )
                )
    for g in model.globals.values():
        if g.is_atomic or g.is_sync or g.is_const:
            continue
        if g.type_text.startswith(("constexpr", "static constexpr")):
            continue
        if g.owner is None:
            out.append(
                Violation(
                    "owner-missing", model.path, g.line,
                    f"global {g.name} ({g.type_text}) is mutable shared "
                    "state with no declared owner",
                )
            )

    for fn in model.funcs.values():
        out.extend(check_function(fn, model))

    return [
        v for v in out
        if not allowed(ann, v.rule, v.line)
    ]


def owned_fields(model: Model, sname: str) -> Dict[str, Optional[str]]:
    """field -> owner for every field of `sname` that needs one (the
    tier-1 coverage gate asserts none are None for NetLoop/NetConn)."""

    return {
        f.name: f.owner
        for f in model.structs.get(sname, {}).values()
        if field_needs_owner(f)
    }
