"""fabricscan — static analysis for the C++ native plane.

fabriclint (PR 6) checks the FFI seam and fabricverify (PR 7) the
Python concurrency plane; this third sibling covers the side both of
them stop at: the ~5 kLoC of hand-rolled C++ in ``src/tbnet`` +
``src/tbutil`` where the repo's three hardest invariants actually live.
It parses the C++ into a lightweight statement/dataflow model (no clang
— ``cmodel.py`` extends the ``cdecl.py`` philosophy to function bodies)
and runs three passes:

- **wire-bounds** (wirebounds.py): taint dataflow over every function
  reachable from the frame cutter, the meta scanners, and the codec
  table — a wire-derived length reaching an index/memcpy/allocation
  without a dominating bounds check is a violation.
- **ownership** (ownership.py): every mutable field of the
  multi-reactor structures carries a declared owner
  (``// fabricscan: owner(loop|worker|shared|init)``); thread roles
  propagate over the call graph and a loop-owned field touched from
  another role without an atomic/ring/lock is a violation.  PR 9's
  "zero cross-reactor locks" claim, checked instead of commented.
- **plane-parity** (parity.py): the constant surfaces mirrored between
  the planes (PRPC header, RpcMeta field numbers, codec ids, berror
  texts, snappy constants, flag defaults) extracted from both sources
  and diffed at lint time.

Exemptions use fabriclint's grammar — the same marker, the same
enforced-non-empty reason — and fabricscan's rule ids are registered in
``tools.fabriclint.RULES`` (``SCAN_RULES``) so one scanner validates
every annotation in the tree.  The ``// fabricscan: <directive>``
comments (owner/role/locked/borrows/sanitizes/requires-bounded) are a
separate, declarative grammar owned by ``cmodel.py``.

Run everything: ``python -m tools.fabricscan`` (or ``make lint``, which
merges all three tools' exit codes); the same checks run inside tier-1
via tests/test_static_analysis.py.
"""

from __future__ import annotations

from typing import List

from tools.fabriclint import (  # noqa: F401  (re-exported surface)
    REPO_ROOT,
    Violation,
    allowed,
    scan_annotations,
    to_records,
)

# The rule ids this tool owns — defined once in fabriclint.SCAN_RULES
# (where they register into the shared RULES grammar); re-exported here
# so --list-rules/--rule filtering can never drift from the scanner.
from tools.fabriclint import SCAN_RULES as RULES  # noqa: E402


def run_all() -> List[Violation]:
    """Run all three passes; returns unexempted violations."""

    from tools.fabricscan import ownership, parity, wirebounds

    out: List[Violation] = []
    out.extend(wirebounds.check())
    out.extend(ownership.check())
    out.extend(parity.check())
    seen = set()
    unique: List[Violation] = []
    for v in out:
        key = (v.rule, v.path, v.line, v.message)
        if key not in seen:
            seen.add(key)
            unique.append(v)
    return unique
