"""wire-bounds — taint dataflow over the native plane's wire parsers.

The repo's hardest native invariant: **every wire-derived length is
bounds-checked before it touches memory**.  PR 2's `len > n - off`
subtraction idiom, PR 11's `max_decompress_bytes` ceiling, and the
`body_len > max_body` frame caps all exist to enforce it — but until now
nothing checked that every NEW use of a wire length repeats the
discipline.  This pass does, intraprocedurally, over every function
reachable from the frame cutter, the meta scanners, and the codec table:

**Taint sources**
- the value out-param of ``read_varint`` (4th argument);
- assignments from ``get_be32``/``load32le``/``strtol``;
- assignments whose RHS loads a byte out of a buffer (``= in[...]``);
- length-ish fields of wire-derived structs: ``tb_tbus_hdr.body_len`` /
  ``.meta_len`` (the tbus header is raw — callers own its bounds),
  ``PrpcMeta``/``MetaLite`` length fields at consumer sites.

**Sinks** (a tainted value reaching one unguarded is a violation)
- subscript indices (loop guards accepted — the cursor idiom);
- size arguments of mem functions / allocations / iobuf primitives
  (``memcpy``/``memcmp``/``malloc``/``.resize``/``.reserve``/
  ``tb_iobuf_copy_to``/``cutn``/``popn``) — strong guards only;
- pointer arithmetic (``base + len`` assigned to a pointer);
- the buffer-bound argument of ``read_varint`` (arg 2);
- stores through the out-params of a ``// fabricscan: sanitizes(...)``
  function (the declaration that callers may trust its outputs);
- arguments to ``// fabricscan: requires-bounded(argN.field)`` functions
  whose named field is tainted and unguarded at the call site.

**Guards** — a relational comparison of the tainted name against a bound
that is not the live buffer size (comparing a claimed length against
``tb_iobuf_size(...)`` just grows the buffer to meet a hostile claim —
the DoS this pass exists to catch).  Guards in ``for``/``while``
conditions are *weak* (accepted for subscript/deref sinks only); ``if``
conditions and ternaries are *strong*.  A guard against another tainted
value sanitizes only once that value is itself sanitized (the
``meta_len <= body_len <= max_body`` chain).

Boundary contracts (documented in docs/ANALYSIS.md): function parameters
are clean unless the function participates in a contract annotation —
call sites of the checked scope are themselves in scope, so a parameter
fed a tainted argument is caught at the caller.  ``ReqCtx`` construction
sites are checked (every tainted initializer must be sanitized); the
struct's fields are then trusted downstream (run_native's hot path does
not re-check what the cutter already proved).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from tools.fabriclint import Violation, allowed, scan_annotations
from tools.fabricscan import cmodel
from tools.fabricscan.cmodel import CppFunc, Model

# entry points of the checked call graph: the server frame cutter (both
# protocols ride process_frames), the client read paths, the raw tbus
# header parser pair, and the codec table
ROOTS = [
    "process_frames",
    "tb_channel_pump",
    "pump_once",
    "prpc_complete_one",
    "tb_tbus_peek",
    "tb_tbus_cut",
    "codec_decompress",
    "tb_scan_prpc_meta",
]

# wire-derived structs and their length-ish fields (the taint boundary:
# non-length fields — cids, flags, codes — cannot index memory)
WIRE_STRUCT_FIELDS = {
    "tb_tbus_hdr": ("body_len", "meta_len"),
    "PrpcMeta": ("attachment", "svc_len", "mth_len", "auth_len",
                 "req_sub_len"),
    "MetaLite": ("attachment",),
}

_REL = r"(?:<=|>=|==|<(?![<=])|>(?![>=]))"

_SINK_CALL_FNS = (
    "memcpy", "memmove", "memcmp", "malloc",
    "tb_iobuf_copy_to", "tb_iobuf_cutn", "tb_iobuf_popn",
)
_SINK_METHODS = ("resize", "reserve", "assign")


@dataclass
class _Taint:
    token: str          # the tracked lvalue text (may be dotted)
    pos: int            # first tainted position in the body
    sanitized_at: Optional[int] = None  # first strong-guard position
    weak_at: Optional[int] = None       # first (any) guard position
    bounded_by: Optional[str] = None    # tainted bound (chain rule)
    bound_pos: Optional[int] = None


def _balanced(text: str, open_pos: int) -> int:
    """Index one past the matching close paren for the '(' at open_pos."""

    depth = 0
    for i in range(open_pos, len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def _split_args(argtext: str) -> List[str]:
    # depth tracks ()[]{}, NOT <>: `ch->rbuf` and comparisons would skew
    # an angle-bracket count (template-arg commas are always inside the
    # value's own parens in this codebase)
    out, buf, depth = [], [], 0
    for ch in argtext:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf).strip())
    return out


def _loop_intervals(body: str) -> List[Tuple[int, int]]:
    """(start, end) spans of for/while condition parens (weak guards)."""

    out = []
    for m in re.finditer(r"\b(?:for|while)\s*\(", body):
        op = body.index("(", m.start())
        out.append((op, _balanced(body, op)))
    return out


def _in_intervals(pos: int, ivs: List[Tuple[int, int]]) -> bool:
    return any(a <= pos < b for a, b in ivs)


def _find_taints(fn: CppFunc, model: Model) -> Dict[str, _Taint]:
    body = fn.body
    taints: Dict[str, _Taint] = {}

    def add(token: str, pos: int) -> None:
        if token not in taints or pos < taints[token].pos:
            taints[token] = _Taint(token, pos)

    # read_varint value out-param (4th arg)
    for m in re.finditer(r"\bread_varint\s*\(", body):
        end = _balanced(body, m.end() - 1)
        args = _split_args(body[m.end(): end - 1])
        if len(args) == 4:
            v = args[3].lstrip("&").strip()
            if re.fullmatch(r"[\w.>\-]+", v):
                add(v.replace("->", "."), m.start())

    # assignments from wire loaders (the loader must belong to THIS
    # declarator: no commas or CLOSE parens between the `=` and the call,
    # so a multi-declarator line taints each variable separately and a
    # loader inside an earlier call's completed arg list doesn't leak —
    # open parens are allowed so grouped/cast forms like
    # `h = (load32le(p) * kMul) >> shift` still taint)
    for m in re.finditer(
        r"((?:\w+(?:->|\.))*\w+)\s*=(?!=)\s*[^;,)]*?"
        r"\b(?:get_be32|load32le|strtol)\s*\(",
        body,
    ):
        add(m.group(1).replace("->", "."), m.start())

    # assignments loading a byte/word out of a buffer: `x = ...buf[...]`
    for m in re.finditer(
        r"((?:\w+(?:->|\.))*\w+)\s*(?:\|=|=(?!=))\s*[^;]*?\[",
        body,
    ):
        # skip compound lvalues with their own subscript (`out[k] = ...`)
        stmt_start = body.rfind(";", 0, m.start()) + 1
        lhs_region = body[stmt_start: m.start() + len(m.group(1))]
        if "[" in lhs_region.split("=")[0] and "]" in lhs_region.split("=")[0]:
            continue
        # address-of is not a load: `mptr = &mheap[0]` takes a buffer
        # ELEMENT ADDRESS, no wire byte flows into the value
        rhs = body[m.start(): body.find(";", m.start())]
        rhs = rhs.split("=", 1)[1].strip() if "=" in rhs else rhs
        if rhs.startswith("&"):
            continue
        add(m.group(1).replace("->", "."), m.start())

    # wire-struct locals/params
    bounded = {(idx, f) for idx, f in fn.requires_bounded}
    bounded_fields_by_param: Dict[str, Set[str]] = {}
    for idx, fname in bounded:
        if 1 <= idx <= len(fn.params):
            bounded_fields_by_param.setdefault(
                fn.params[idx - 1][1], set()
            ).add(fname)
    for sname, fields in WIRE_STRUCT_FIELDS.items():
        # parameters of that struct type
        for ptype, pname in fn.params:
            if sname in ptype and pname:
                for f in fields:
                    if f in bounded_fields_by_param.get(pname, ()):
                        continue  # contract: caller already bounded it
                    tok = f"{pname}.{f}"
                    if re.search(
                        rf"\b{re.escape(pname)}\s*(?:->|\.)\s*{f}\b", body
                    ):
                        add(tok, 0)
        # locals: `tb_tbus_hdr hdr;` / `PrpcMeta pm = scan_prpc_meta(...)`
        for m in re.finditer(rf"\b{sname}\s+(\w+)\s*[;=]", body):
            var = m.group(1)
            for f in fields:
                if re.search(rf"\b{re.escape(var)}\s*\.\s*{f}\b", body):
                    add(f"{var}.{f}", m.start())
    return taints


def _token_re(token: str) -> str:
    """Regex matching the token with -> and . spellings unified."""

    parts = [re.escape(p) for p in token.split(".")]
    return r"(?<![\w.])" + r"\s*(?:->|\.)\s*".join(parts) + r"(?![\w(])"


def _is_live_size(other: str) -> bool:
    """Is this bound the LIVE size of a growable read buffer?  Comparing
    a claimed length against ``tb_iobuf_size(<rbuf>)`` just waits for
    more hostile bytes to arrive (the DoS class this pass exists to
    catch), as does ``nbytes`` (the iobuf's own size field inside
    tbutil).  Comparing against the size of an already-cut frame body
    (the reactor ``scratch``, a pump body) is a REAL bound — the frame's
    total was capped before the cut — so those pass."""

    if "nbytes" in other:
        return True
    return "tb_iobuf_size" in other and "rbuf" in other


def _guard_pass(fn: CppFunc, taints: Dict[str, _Taint],
                loops: List[Tuple[int, int]]) -> bool:
    body = fn.body
    changed = False
    for t in taints.values():
        if t.sanitized_at is not None:
            continue
        tre = _token_re(t.token)
        # masking caps the value outright: `h &= kTableMask;`
        for m in re.finditer(rf"{tre}\s*&=\s*[^;]+;", body):
            pos = m.start()
            if t.sanitized_at is None or pos < t.sanitized_at:
                t.sanitized_at = pos
                if t.weak_at is None or pos < t.weak_at:
                    t.weak_at = pos
                changed = True
        # relational comparison against a bound; the token may sit inside
        # a small additive expression (`sl + 1 + mn < sizeof full`)
        for m in re.finditer(
            rf"(?:{tre}\s*(?:[-+][\w.\s>+\-]{{0,40}}?)?{_REL}"
            rf"\s*(?P<rhs>[^;&|?,]{{0,80}})"
            rf"|(?<![<>=!])(?P<lhs>[^;&|?,(]{{0,80}}?){_REL}\s*{tre})",
            body,
        ):
            other = (m.group("rhs") or m.group("lhs") or "").strip()
            if _is_live_size(other):
                continue
            if re.match(r"0[^\w.]", other + " "):
                continue  # `len > 0` is a sign/emptiness check, not a bound
            # a bound that is itself tainted only counts once that bound
            # is clean — sanitized before this guard, or within the SAME
            # statement (the `meta > body || body > max` kill idiom
            # checks both halves on one condition)
            dep = None
            for u in taints.values():
                if u.token == t.token:
                    continue
                if re.search(_token_re(u.token), other):
                    dep = u
                    break
            pos = m.start()
            if dep is not None:
                stmt_end = body.find(";", pos)
                stmt_end = len(body) if stmt_end < 0 else stmt_end
                if dep.sanitized_at is None or dep.sanitized_at > stmt_end:
                    continue
            if _in_intervals(pos, loops):
                if t.weak_at is None or pos < t.weak_at:
                    t.weak_at = pos
                    changed = True
            else:
                if t.sanitized_at is None or pos < t.sanitized_at:
                    t.sanitized_at = pos
                    if t.weak_at is None or pos < t.weak_at:
                        t.weak_at = pos
                    changed = True
    return changed


def _find_guards(fn: CppFunc, taints: Dict[str, _Taint]) -> None:
    body = fn.body
    loops = _loop_intervals(body)
    # iterate: chains (`meta_len <= body_len` sanitizes meta_len once
    # body_len is sanitized) need a fixpoint
    for _ in range(4):
        if not _guard_pass(fn, taints, loops):
            break
    # propagate through simple copies: `W = <expr containing V>` where V
    # unsanitized at the copy makes W tainted from there (already covered
    # when the RHS loads from a buffer; here: plain var-to-var copies)
    for t in list(taints.values()):
        tre = _token_re(t.token)
        for m in re.finditer(
            rf"((?:\w+(?:->|\.))*\w+)\s*=\s*[^;=][^;]*?{tre}", fn.body
        ):
            dst = m.group(1).replace("->", ".")
            if dst == t.token or dst in taints:
                continue
            if t.sanitized_at is not None and t.sanitized_at < m.start():
                continue  # copy of an already-sanitized value is clean
            taints[dst] = _Taint(dst, m.start())
    # (extra rounds of guard search for the propagated tokens)
    for _ in range(2):
        if not _guard_pass(fn, taints, loops):
            break


def _sinks(
    fn: CppFunc, taints: Dict[str, _Taint], model: Model
) -> List[Tuple[int, str, _Taint, bool]]:
    """(pos, description, taint, weak_ok) for every tainted sink use."""

    body = fn.body
    out: List[Tuple[int, str, _Taint, bool]] = []
    for t in taints.values():
        tre = _token_re(t.token)
        # subscript index
        for m in re.finditer(rf"\[[^\][]{{0,60}}{tre}[^\][]{{0,60}}\]", body):
            out.append((m.start(), f"subscript index `{t.token}`", t, True))
        # pointer arithmetic assigned somewhere
        for m in re.finditer(
            rf"=\s*[\w.>\-]+\s*\+\s*{tre}|=\s*{tre}\s*\+\s*[\w.>\-]+", body
        ):
            out.append(
                (m.start(), f"pointer arithmetic with `{t.token}`", t, False)
            )
        # growth methods
        for m in re.finditer(
            rf"\.\s*(?:{'|'.join(_SINK_METHODS)})\s*\(", body
        ):
            end = _balanced(body, m.end() - 1)
            if re.search(tre, body[m.end(): end - 1]):
                out.append(
                    (m.start(),
                     f"allocation/growth sized by `{t.token}`", t, False)
                )
    # call-argument sinks
    for m in re.finditer(
        rf"\b(?:{'|'.join(_SINK_CALL_FNS)})\s*\(", body
    ):
        end = _balanced(body, body.index("(", m.start()))
        argtext = body[body.index("(", m.start()) + 1: end - 1]
        for t in taints.values():
            if re.search(_token_re(t.token), argtext):
                name = body[m.start(): body.index("(", m.start())]
                out.append(
                    (m.start(), f"`{t.token}` reaches {name}()", t, False)
                )
    # read_varint's buffer bound (arg 2)
    for m in re.finditer(r"\bread_varint\s*\(", body):
        end = _balanced(body, m.end() - 1)
        args = _split_args(body[m.end(): end - 1])
        if len(args) == 4:
            for t in taints.values():
                if re.search(_token_re(t.token), args[1]):
                    out.append(
                        (m.start(),
                         f"`{t.token}` used as read_varint bound", t, False)
                    )
    # stores through sanitizing out-params
    for pname in fn.sanitizes:
        for m in re.finditer(
            rf"\*\s*{re.escape(pname)}\s*=\s*([^;]+);", body
        ):
            rhs = m.group(1)
            for t in taints.values():
                if re.search(_token_re(t.token), rhs):
                    out.append(
                        (m.start(),
                         f"tainted `{t.token}` escapes through sanitized "
                         f"out-param *{pname}", t, False)
                    )
    # requires-bounded call sites
    for callee_q in fn.calls:
        callee = model.funcs.get(callee_q)
        if callee is None or not callee.requires_bounded:
            continue
        for m in re.finditer(rf"\b{re.escape(callee.name)}\s*\(", body):
            end = _balanced(body, body.index("(", m.start()))
            args = _split_args(body[body.index("(", m.start()) + 1: end - 1])
            for idx, fname in callee.requires_bounded:
                if idx > len(args):
                    continue
                base = args[idx - 1].lstrip("&").strip()
                if not re.fullmatch(r"[\w.>\-]+", base):
                    continue
                tok = f"{base.replace('->', '.')}.{fname}"
                t = taints.get(tok)
                if t is not None:
                    out.append(
                        (m.start(),
                         f"`{tok}` passed to {callee.name}() which requires "
                         f"it bounded", t, False)
                    )
    # ReqCtx construction: every tainted initializer must be sanitized
    for m in re.finditer(r"\bReqCtx\s+\w+\s*\{", body):
        close = body.find("}", m.end())
        inits = body[m.end(): close]
        for t in taints.values():
            if re.search(_token_re(t.token), inits):
                out.append(
                    (m.start(),
                     f"tainted `{t.token}` flows into ReqCtx (trusted "
                     "downstream)", t, False)
                )
    return out


def analyze_function(fn: CppFunc, model: Model) -> List[Violation]:
    taints = _find_taints(fn, model)
    if not taints:
        return []
    _find_guards(fn, taints)
    out: List[Violation] = []
    seen: Set[Tuple[str, int]] = set()
    for pos, desc, t, weak_ok in _sinks(fn, taints, model):
        ok_at = t.weak_at if weak_ok else t.sanitized_at
        if ok_at is not None and ok_at <= pos:
            continue
        line = cmodel.line_of(fn, pos)
        key = (t.token, line)
        if key in seen:
            continue
        seen.add(key)
        out.append(
            Violation(
                "wire-bounds", model_path_of(fn, model), line,
                f"{fn.qname}: {desc} with no dominating bounds check "
                f"(tainted at line {cmodel.line_of(fn, t.pos)})",
            )
        )
    return out


def model_path_of(fn: CppFunc, model: Model) -> str:
    # every CppFunc records the file it was parsed from (merge_models
    # preserves it), so a tbutil helper reached from a tbnet root reports
    # at its real path:line instead of indexing into the wrong file
    return fn.path or model.path.split("+", 1)[0]


def checked_functions(model: Model) -> Set[str]:
    return cmodel.reachable(model, ROOTS)


def check(
    tbnet_text: Optional[str] = None, tbutil_text: Optional[str] = None
) -> List[Violation]:
    model = cmodel.parse_native_plane(tbnet_text, tbutil_text)
    out: List[Violation] = []
    anns = {
        cmodel.TBNET_CC: scan_annotations(cmodel.TBNET_CC, tbnet_text),
        cmodel.TBUTIL_CC: scan_annotations(cmodel.TBUTIL_CC, tbutil_text),
    }
    for root in ROOTS:
        if root not in model.funcs:
            out.append(
                Violation(
                    "scan-parse", model.path.split("+")[0], 1,
                    f"wire-bounds root {root!r} not found in the model — "
                    "the cutter call graph is unchecked",
                )
            )
    reach = checked_functions(model)
    for q in sorted(reach):
        fn = model.funcs[q]
        for v in analyze_function(fn, model):
            ann = anns.get(v.path)
            if ann is not None and allowed(ann, "wire-bounds", v.line):
                continue
            out.append(v)
    return out
