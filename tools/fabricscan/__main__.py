"""CLI: ``python -m tools.fabricscan`` (one third of the ``make lint``
entry point; the three tools' exit codes are merged there).

Runs the wire-bounds, ownership, and plane-parity passes over
src/tbnet + src/tbutil and prints violations one per line
(``path:line: [rule] message``); exits 1 when any survive their
annotations.

- ``--json``: machine-readable report — a JSON array of
  ``{rule, file, line, reason}`` records on stdout (the same schema as
  the fabriclint/fabricverify CLIs), so CI tooling can diff violation
  sets across commits.
- ``--rule <name>`` filters to one rule id; ``--list-rules`` prints the
  ids this tool owns.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    from tools.fabricscan import RULES, run_all, to_records

    ap = argparse.ArgumentParser(prog="fabricscan")
    ap.add_argument("--rule", help="only report this rule id")
    ap.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit"
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit {rule, file, line, reason} records as a JSON array",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for r in RULES:
            print(r)
        return 0
    violations = run_all()
    if args.rule:
        violations = [v for v in violations if v.rule == args.rule]
    if args.json:
        print(json.dumps(to_records(violations), indent=2))
        return 1 if violations else 0
    for v in violations:
        print(v)
    if violations:
        print(f"fabricscan: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("fabricscan: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
