"""Lightweight C++ statement/dataflow model for the tb native plane.

fabriclint's ``cdecl.py`` models the *declarations* of the C ABI; this
module extends the same philosophy to function *bodies*: a tokenizing,
deliberately non-general parser that extracts, from ``src/tbnet/tbnet.cc``
and ``src/tbutil/tbutil.cc``:

- every function definition (free functions, anonymous-namespace helpers,
  struct methods inline and out-of-line) with its parameter list and body
  text anchored to absolute line numbers;
- every struct definition with its field declarations classified as
  atomic / sync-primitive / const / plain-mutable;
- module-level globals;
- a call graph (callee names resolved against the defined function set);
- the ``// fabricscan:`` annotation directives that drive the ownership
  and wire-bounds passes.

The sources are hand-written C++ in a narrow idiom (no templates beyond
``std::`` containers in field types, no overloading of the analyzed
functions, no macros in bodies), so a few hundred lines of scanner cover
them completely — and anything the scanner cannot classify is reported
via ``Model.unparsed`` (the cdecl discipline: an unparsed definition is
an unchecked definition, which the clean gate turns into a violation).

Annotation directives (C++ comments; distinct from the shared
``// fabriclint: allow(rule) reason`` exemption grammar, which stays
owned by tools/fabriclint):

``// fabricscan: owner(loop|worker|shared|init)``
    on a struct field or global: who may touch it (see ownership.py).
``// fabricscan: role(loop|worker|python|init|stop)``
    on a function: the thread context(s) it is entered from (seeds for
    call-graph propagation).
``// fabricscan: locked``
    on a function: its callers hold the guarding mutex (the ``_locked``
    suffix convention, made checkable).
``// fabricscan: borrows(Type[, Type...])``
    on a function: it accesses instances of these checked struct types
    through its parameters, and the instance's ownership is the CALLER's
    obligation at the call site (per-instance contexts like ZCtx).
``// fabricscan: sanitizes(name[, name...])``
    on a function: its out-parameters of these names are bounds-checked
    before being stored (wirebounds verifies the stores ARE guarded, and
    callers treat the outputs as clean).
``// fabricscan: requires-bounded(argN.field[, ...])``
    on a function: callers must pass the N-th argument (1-based) with
    ``field`` already bounds-checked; inside the function the field is
    treated as sanitized.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.fabriclint import REPO_ROOT

TBNET_CC = os.path.join(REPO_ROOT, "src", "tbnet", "tbnet.cc")
TBUTIL_CC = os.path.join(REPO_ROOT, "src", "tbutil", "tbutil.cc")

_KEYWORDS = {
    "if", "for", "while", "switch", "return", "sizeof", "catch", "do",
    "else", "new", "delete", "case", "default", "goto", "static_cast",
    "reinterpret_cast", "const_cast", "dynamic_cast", "alignas", "decltype",
}

_DIRECTIVE_RE = re.compile(r"//\s*fabricscan:\s*([a-z-]+)(?:\(([^)]*)\))?")


@dataclass
class Directive:
    kind: str        # owner | role | locked | borrows | sanitizes | requires-bounded
    args: List[str]
    line: int


@dataclass
class CppField:
    struct: str
    name: str
    type_text: str
    line: int
    owner: Optional[str] = None     # from owner(...) directive
    is_atomic: bool = False
    is_sync: bool = False
    is_const: bool = False


@dataclass
class CppGlobal:
    name: str
    type_text: str
    line: int
    owner: Optional[str] = None
    is_atomic: bool = False
    is_sync: bool = False
    is_const: bool = False


@dataclass
class CppFunc:
    name: str                 # short name (method name for methods)
    qname: str                # Struct::name for methods, else name
    struct: Optional[str]     # enclosing/owning struct for methods
    line: int                 # line of the signature
    body: str                 # body text, braces excluded
    body_offset_line: int     # absolute line number of the body's first line
    params: List[Tuple[str, str]] = field(default_factory=list)  # (type, name)
    is_ctor: bool = False
    roles: Set[str] = field(default_factory=set)       # seeded + propagated
    seeded_roles: Set[str] = field(default_factory=set)
    locked: bool = False
    borrows: Set[str] = field(default_factory=set)
    sanitizes: Set[str] = field(default_factory=set)
    requires_bounded: List[Tuple[int, str]] = field(default_factory=list)
    calls: Set[str] = field(default_factory=set)       # qnames of callees
    path: str = ""            # source file (survives merge_models)


@dataclass
class Model:
    path: str
    funcs: Dict[str, CppFunc] = field(default_factory=dict)       # by qname
    by_short: Dict[str, List[CppFunc]] = field(default_factory=dict)
    structs: Dict[str, Dict[str, CppField]] = field(default_factory=dict)
    struct_lines: Dict[str, int] = field(default_factory=dict)
    globals: Dict[str, CppGlobal] = field(default_factory=dict)
    unparsed: List[Tuple[int, str]] = field(default_factory=list)
    directives: Dict[int, List[Directive]] = field(default_factory=dict)

    def directive_for(
        self, line: int, kind: str, lookback: int = 2
    ) -> Optional[Directive]:
        """A directive applies on its own line or up to ``lookback``
        lines above (a function signature may carry a one/two-line
        comment block).  Field directives pass ``lookback=0``: fields
        are consecutive single lines, so a lookback would let an
        unannotated field silently inherit its neighbour's owner()
        instead of firing owner-missing."""

        for ln in range(line, line - lookback - 1, -1):
            for d in self.directives.get(ln, ()):
                if d.kind == kind:
                    return d
        return None


def _scan_directives(text: str) -> Dict[int, List[Directive]]:
    out: Dict[int, List[Directive]] = {}
    for i, ln in enumerate(text.split("\n"), 1):
        if "fabricscan:" not in ln:
            continue
        for m in _DIRECTIVE_RE.finditer(ln):
            kind = m.group(1)
            args = [
                a.strip() for a in (m.group(2) or "").split(",") if a.strip()
            ]
            out.setdefault(i, []).append(Directive(kind, args, i))
    return out


def _blank_comments_and_strings(text: str) -> str:
    """Blank comments and string/char literal CONTENTS, preserving
    newlines and overall offsets so line math stays exact."""

    out = list(text)
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            for k in range(i, j + 2):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 2
        elif c == '"' or c == "'":
            q = c
            j = i + 1
            while j < n:
                if text[j] == "\\":
                    j += 2
                    continue
                if text[j] == q:
                    break
                j += 1
            for k in range(i + 1, min(j, n)):
                if out[k] != "\n":
                    out[k] = " "
            i = min(j, n - 1) + 1
        else:
            i += 1
    return "".join(out)


def _blank_preprocessor(text: str) -> str:
    lines = text.split("\n")
    for i, ln in enumerate(lines):
        if ln.lstrip().startswith("#"):
            lines[i] = ""
    return "\n".join(lines)


_SYNC_TYPES = ("std::mutex", "std::condition_variable", "std::thread")

_FIELD_RE = re.compile(
    r"^(?P<type>.+?[\s*&>])(?P<name>[A-Za-z_]\w*)\s*(?:\[[^\]]*\]\s*)*"
    r"(?:\{[^{}]*\}|=[^;]*)?$",
    re.S,
)


def _split_top_commas(seg: str) -> List[str]:
    parts, buf, depth = [], [], 0
    for ch in seg:
        if ch in "<({[":
            depth += 1
        elif ch in ">)}]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    parts.append("".join(buf))
    return parts


def _parse_field(segment: str) -> Optional[List[Tuple[str, str]]]:
    """One struct-field / global declaration -> [(type_text, name), ...]
    (multi-declarator ``bool a = false, b = false;`` yields several)."""

    seg = " ".join(segment.split())
    seg = re.sub(r"\balignas\([^)]*\)\s*", "", seg)
    for skip in ("typedef ", "using ", "friend ", "template", "enum "):
        if seg.startswith(skip):
            return None
    if re.fullmatch(r"(?:struct|class)\s+\w+", seg):
        return None  # forward declaration, not a data member
    if "(" in seg.split("{")[0].split("=")[0]:
        return None  # method declaration / fn-ptr field: not a data field
    parts = _split_top_commas(seg)
    m = _FIELD_RE.match(parts[0].strip())
    if m is None:
        return None
    out = [(m.group("type").strip(), m.group("name"))]
    for extra in parts[1:]:
        em = re.match(r"^\s*([A-Za-z_]\w*)\s*(?:\{[^{}]*\}|=.*)?$", extra)
        if em:
            out.append((m.group("type").strip(), em.group(1)))
    return out


def _classify(type_text: str) -> Tuple[bool, bool, bool]:
    is_atomic = "std::atomic" in type_text
    is_sync = any(s in type_text for s in _SYNC_TYPES)
    is_const = type_text.startswith(("const ", "constexpr ", "static constexpr"))
    return is_atomic, is_sync, is_const


_PARAM_NAME_RE = re.compile(r"^(.*?)([A-Za-z_]\w*)(\s*\[\s*\d*\s*\])?$")


def _parse_params(arglist: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    arglist = arglist.strip()
    if arglist in ("", "void"):
        return out
    depth = 0
    parts, buf = [], []
    for ch in arglist:
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    parts.append("".join(buf))
    for raw in parts:
        raw = " ".join(raw.split())
        if not raw:
            continue
        raw = raw.split("=")[0].strip()  # default args
        m = _PARAM_NAME_RE.match(raw)
        if m and m.group(1).strip():
            out.append((m.group(1).strip(), m.group(2)))
        else:
            out.append((raw, ""))  # unnamed parameter
    return out


def _match_function_header(segment: str) -> Optional[Tuple[str, str, str]]:
    """If `segment` (text before a '{' at decl depth) is a function
    definition header, return (ret_and_quals, name, arglist)."""

    seg = " ".join(segment.split())
    # drop a ctor-initializer list: everything after the LAST ')' that is
    # followed by ':' (but not '::')
    # find the argument list: the last top-level (...) group
    depth = 0
    close = -1
    opens: List[int] = []
    pairs: List[Tuple[int, int]] = []
    for i, ch in enumerate(seg):
        if ch == "(":
            depth += 1
            opens.append(i)
        elif ch == ")":
            depth -= 1
            if depth == 0 and opens:
                pairs.append((opens[0], i))
                opens = []
    if not pairs:
        return None
    # the FIRST paren group whose suffix looks like a function tail is the
    # argument list (a ctor-initializer list after it may carry more
    # parens: `NetConn() : PollObj(0)` — the arglist is the first group)
    for op, cl in pairs:
        tail = seg[cl + 1:].strip()
        if tail and not re.fullmatch(
            r"(?:const|noexcept|override|final)?\s*(?::(?!:).*)?", tail
        ):
            continue
        head = seg[:op].rstrip()
        m = re.search(r"(~?[A-Za-z_][\w:]*)\s*$", head)
        if not m:
            return None
        name = m.group(1).lstrip("~")
        short = name.rsplit("::", 1)[-1]
        if short in _KEYWORDS:
            return None
        ret = head[: m.start()].strip()
        if ret.endswith(("=", "return", ",")):  # assignment w/ call, etc.
            return None
        return ret, name, seg[op + 1: cl]
    return None


def parse_file(path: str, text: Optional[str] = None) -> Model:
    if text is None:
        with open(path, "r") as fh:
            text = fh.read()
    model = Model(path=path)
    model.directives = _scan_directives(text)
    clean = _blank_preprocessor(_blank_comments_and_strings(text))

    n = len(clean)
    line = 1
    i = 0
    seg_start = 0
    seg_line = 1
    # context stack: list of ("namespace"|"struct"|"enum"|"extern", name)
    ctx: List[Tuple[str, str]] = []

    def cur_struct() -> Optional[str]:
        for kind, name in reversed(ctx):
            if kind == "struct":
                return name
        return None

    def attach_fn(ret: str, name: str, arglist: str, sig_line: int,
                  body: str, body_line: int) -> None:
        struct = cur_struct()
        if "::" in name:
            struct, short = name.rsplit("::", 1)
        else:
            short = name
        qname = f"{struct}::{short}" if struct else short
        fn = CppFunc(
            name=short, qname=qname, struct=struct, line=sig_line,
            body=body, body_offset_line=body_line,
            params=_parse_params(arglist),
            is_ctor=(struct is not None and short == struct)
            or short.startswith("~"),
        )
        d = model.directive_for(sig_line, "role")
        if d:
            fn.seeded_roles = set(d.args)
        if model.directive_for(sig_line, "locked"):
            fn.locked = True
        d = model.directive_for(sig_line, "borrows")
        if d:
            fn.borrows = set(d.args)
        d = model.directive_for(sig_line, "sanitizes")
        if d:
            fn.sanitizes = set(d.args)
        d = model.directive_for(sig_line, "requires-bounded")
        if d:
            for a in d.args:
                m = re.fullmatch(r"arg(\d+)\.(\w+)", a)
                if m:
                    fn.requires_bounded.append((int(m.group(1)), m.group(2)))
                else:
                    model.unparsed.append(
                        (sig_line, f"bad requires-bounded arg {a!r}")
                    )
        model.funcs[qname] = fn
        model.by_short.setdefault(short, []).append(fn)

    def attach_field(segment: str, at_line: int) -> None:
        struct = cur_struct()
        parsed = _parse_field(segment)
        if parsed is None:
            s = " ".join(segment.split())
            # method declarations / defaulted dtors inside a struct are
            # not data fields, and forward declarations (`struct NetLoop;`)
            # carry no state; skip both quietly
            if (
                s
                and "(" not in s
                and not s.startswith(("public", "private", "protected"))
                and not re.fullmatch(r"(?:struct|class)\s+\w+", s)
            ):
                model.unparsed.append((at_line, s[:80]))
            return
        d = model.directive_for(at_line, "owner", lookback=0)
        owner = d.args[0] if d and d.args else None
        for type_text, name in parsed:
            is_atomic, is_sync, is_const = _classify(type_text)
            if struct is not None:
                model.structs.setdefault(struct, {})[name] = CppField(
                    struct, name, type_text, at_line, owner,
                    is_atomic, is_sync, is_const,
                )
            else:
                model.globals[name] = CppGlobal(
                    name, type_text, at_line, owner,
                    is_atomic, is_sync, is_const,
                )

    pending = ""  # declaration text preceding a brace initializer

    def _consume_balanced(j: int) -> int:
        nonlocal line
        depth = 1
        while j < n and depth > 0:
            cj = clean[j]
            if cj == "{":
                depth += 1
            elif cj == "}":
                depth -= 1
            elif cj == "\n":
                line += 1
            j += 1
        return j

    while i < n:
        ch = clean[i]
        if ch == "\n":
            line += 1
            i += 1
            continue
        if not pending and not clean[seg_start:i].strip() and not ch.isspace():
            seg_line = line
        if ch == ";":
            segment = (pending + " " + clean[seg_start:i]).strip()
            pending = ""
            at_decl_depth = not ctx or ctx[-1][0] in (
                "namespace", "struct", "extern"
            )
            if segment and at_decl_depth:
                in_struct = bool(ctx) and ctx[-1][0] == "struct"
                if in_struct or "(" not in segment.split("=")[0]:
                    attach_field(segment, seg_line)
            i += 1
            seg_start = i
            continue
        if ch == "{":
            segment = clean[seg_start:i].strip()
            seg1 = " ".join(segment.split())
            sm = re.match(
                r"^(?:typedef\s+)?(?:struct|class)\s+([A-Za-z_]\w*)"
                r"(?:\s*(?::|final).*)?$",
                seg1,
            )
            if seg1.startswith("namespace") and not pending:
                ctx.append(
                    ("namespace",
                     seg1.split()[-1] if len(seg1.split()) > 1 else "")
                )
                i += 1
                seg_start = i
                continue
            # extern "C" { ... }: a transparent linkage block (string
            # contents are blanked, so the segment reads `extern " "`)
            if re.fullmatch(r'extern\s*"[^"]*"', seg1) and not pending:
                ctx.append(("extern", ""))
                i += 1
                seg_start = i
                continue
            if (seg1.startswith("enum") and "(" not in seg1 and not pending):
                ctx.append(("enum", ""))
                i += 1
                seg_start = i
                continue
            if sm and "(" not in seg1 and not pending:
                ctx.append(("struct", sm.group(1)))
                model.struct_lines[sm.group(1)] = seg_line
                i += 1
                seg_start = i
                continue
            fh = _match_function_header(segment) if not pending else None
            if fh is not None:
                ret, name, arglist = fh
                body_line = line
                j = _consume_balanced(i + 1)
                body = clean[i + 1: j - 1]
                attach_fn(ret, name, arglist, seg_line, body, body_line)
                i = j
                seg_start = i
                continue
            # brace initializer on a declaration (`std::atomic<u32> x{0};`):
            # stash the declaration text, skip the initializer, and let the
            # terminating ';' attach the field/global
            pending = (pending + " " + segment).strip()
            i = _consume_balanced(i + 1)
            seg_start = i
            continue
        if ch == "}":
            if ctx:
                ctx.pop()
            i += 1
            seg_start = i
            continue
        i += 1

    for fn in model.funcs.values():
        fn.path = path
    _resolve_calls(model)
    return model


_CALL_RE = re.compile(r"(\.|->)?\s*\b([A-Za-z_]\w*)\s*\(")


def _resolve_calls(model: Model) -> None:
    for fn in model.funcs.values():
        for m in _CALL_RE.finditer(fn.body):
            short = m.group(2)
            if short in _KEYWORDS:
                continue
            cands = model.by_short.get(short)
            if not cands:
                continue
            is_member_call = m.group(1) is not None
            for cand in cands:
                if cand.struct is not None and not is_member_call:
                    # a struct method invoked without an object: only via
                    # unqualified call inside the same struct
                    if fn.struct != cand.struct:
                        continue
                fn.calls.add(cand.qname)
        # thread/ctor-style callee references without '(' directly after
        # (std::thread(loop_run, ...), emplace_back(pool_worker, s, w))
        for m in re.finditer(r"\b(thread|emplace_back)\s*\(\s*([A-Za-z_]\w*)",
                             fn.body):
            cands = model.by_short.get(m.group(2))
            if cands:
                for cand in cands:
                    if cand.struct is None:
                        fn.calls.add(cand.qname)


def merge_models(models: List[Model]) -> Model:
    merged = Model(path="+".join(m.path for m in models))
    for m in models:
        merged.funcs.update(m.funcs)
        for k, v in m.by_short.items():
            merged.by_short.setdefault(k, []).extend(v)
        merged.structs.update(m.structs)
        merged.struct_lines.update(m.struct_lines)
        merged.globals.update(m.globals)
        merged.unparsed.extend(m.unparsed)
        for k, v in m.directives.items():
            merged.directives.setdefault(k, []).extend(v)
    # re-resolve calls so cross-file edges (tbnet -> tbutil) appear
    _resolve_calls(merged)
    return merged


def parse_native_plane(
    tbnet_text: Optional[str] = None, tbutil_text: Optional[str] = None
) -> Model:
    """The merged model of src/tbnet/tbnet.cc + src/tbutil/tbutil.cc.
    Text overrides exist for the seeded-mutation meta-tests."""

    a = parse_file(TBNET_CC, text=tbnet_text)
    b = parse_file(TBUTIL_CC, text=tbutil_text)
    return merge_models([a, b])


def reachable(model: Model, roots: List[str]) -> Set[str]:
    """Call-graph closure from root function qnames (unknown roots are
    the caller's problem — report them as coverage violations)."""

    seen: Set[str] = set()
    stack = [r for r in roots if r in model.funcs]
    while stack:
        q = stack.pop()
        if q in seen:
            continue
        seen.add(q)
        for callee in model.funcs[q].calls:
            if callee not in seen:
                stack.append(callee)
    return seen


def line_of(fn: CppFunc, pos: int) -> int:
    """Absolute line number of a character offset inside fn.body."""

    return fn.body_offset_line + fn.body.count("\n", 0, pos)
