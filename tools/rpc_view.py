#!/usr/bin/env python
"""rpc_view — print the contents of rpc_dump sample files (reference
tools/rpc_view).

Usage:
    python tools/rpc_view.py ./rpc_dump/requests.1234.0000
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="+", help="dump files")
    p.add_argument("--max-payload", type=int, default=64, help="bytes shown")
    args = p.parse_args(argv)

    from incubator_brpc_tpu.rpc.dump import load_dump_file

    n = 0
    for path in args.paths:
        for meta, payload, attachment in load_dump_file(path):
            preview = payload[: args.max_payload]
            print(
                f"[{n}] {meta.service}.{meta.method} "
                f"payload={len(payload)}B attachment={len(attachment)}B "
                f"compress={meta.compress or '-'} log_id={meta.log_id} "
                f"| {preview!r}"
            )
            n += 1
    print(f"{n} samples")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
