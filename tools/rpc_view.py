#!/usr/bin/env python
"""rpc_view — inspect a server or rpc_dump samples (reference
tools/rpc_view: a proxy server that forwards any path to the target's
builtin portal and annotates the rendering, rpc_view.cpp:23-60; plus the
dump-file printer role of rpc_replay's sibling tooling).

Two modes:

  Proxy a live server's portal (the reference tool's shape):
    python tools/rpc_view.py --serve 8888 --target 127.0.0.1:8000
    # then browse http://127.0.0.1:8888/status /vars /rpcz /protobufs ...

  Print rpc_dump sample files:
    python tools/rpc_view.py ./rpc_dump/requests.1234.0000
    python tools/rpc_view.py --service users --method get --json dump.0000
"""

from __future__ import annotations

import argparse
import json
import sys


def make_proxy_server(target: str):
    """Build (but do not start) the rpc_view front server: every path
    relays to the target's portal, renderings are tagged with the origin
    (rpc_view.cpp:52-60). Returns the Server, or None on a bad target."""
    from incubator_brpc_tpu.protocol.http import http_call
    from incubator_brpc_tpu.rpc import Server, ServerOptions

    host, _, tport = target.rpartition(":")
    if not host or not tport.isdigit():
        return None

    def relay(frame):
        from urllib.parse import urlencode

        path = frame.path
        if frame.query:
            # values arrived URL-decoded (parse_qsl): re-encode, or spaces
            # and '&'/'=' inside values would corrupt the target's request
            path = f"{path}?{urlencode(frame.query)}"
        try:
            status, headers, body = http_call(
                host, int(tport), path, method=frame.method,
                body=frame.body if isinstance(frame.body, bytes) else b"",
                timeout=15,
            )
        except OSError as e:
            return 502, "text/plain", (
                f"rpc_view: target {target} unreachable: {e}\n".encode()
            )
        ctype = headers.get("content-type", "text/plain")
        # visually tag HUMAN renderings with the target (rpc_view.cpp:52-60)
        # — never binary or machine-parsed payloads (/dir files, pprof
        # folded output), which must relay byte-identical
        if "html" in ctype and b"</body>" in body:
            tag = f"<hr><i>rpc_view of {target}</i>".encode()
            body = body.replace(b"</body>", tag + b"</body>", 1)
        elif ctype.startswith("text/plain") and not path.startswith("/pprof"):
            body = f"# rpc_view of {target}{path}\n".encode() + body
        return status, ctype, body

    # no builtin pages on the front: the whole point is viewing the
    # TARGET's portal, so every path — /status, /vars, /rpcz — relays
    srv = Server(ServerOptions(has_builtin_services=False))
    srv.add_http_handler("/", relay)  # prefix: every path relays
    return srv


def serve_proxy(port: int, target: str) -> int:
    srv = make_proxy_server(target)
    if srv is None:
        print(f"bad --target {target!r} (want host:port)", file=sys.stderr)
        return 2
    if not srv.start(port):
        print(f"cannot listen on {port}", file=sys.stderr)
        return 1
    print(f"rpc_view of {target} on http://127.0.0.1:{srv.port}/  (Ctrl-C stops)")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def print_dumps(args) -> int:
    from incubator_brpc_tpu.rpc.dump import load_dump_file

    n = shown = 0
    for path in args.paths:
        for meta, payload, attachment in load_dump_file(path):
            n += 1
            if args.service and meta.service != args.service:
                continue
            if args.method and meta.method != args.method:
                continue
            shown += 1
            if args.json:
                print(
                    json.dumps(
                        {
                            "service": meta.service,
                            "method": meta.method,
                            "payload_len": len(payload),
                            "attachment_len": len(attachment),
                            "compress": meta.compress,
                            "log_id": meta.log_id,
                            "payload_head": payload[: args.max_payload].hex(),
                        }
                    )
                )
            else:
                preview = payload[: args.max_payload]
                print(
                    f"[{shown - 1}] {meta.service}.{meta.method} "
                    f"payload={len(payload)}B attachment={len(attachment)}B "
                    f"compress={meta.compress or '-'} log_id={meta.log_id} "
                    f"| {preview!r}"
                )
    print(f"{shown}/{n} samples", file=sys.stderr if args.json else sys.stdout)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="*", help="dump files (dump mode)")
    p.add_argument("--max-payload", type=int, default=64, help="bytes shown")
    p.add_argument("--service", help="only samples of this service")
    p.add_argument("--method", help="only samples of this method")
    p.add_argument("--json", action="store_true", help="one JSON line per sample")
    p.add_argument("--serve", type=int, help="proxy mode: listen on this port")
    p.add_argument("--target", help="proxy mode: host:port of the server to view")
    args = p.parse_args(argv)

    if args.serve is not None:
        if not args.target:
            p.error("--serve requires --target host:port")
        return serve_proxy(args.serve, args.target)
    if not args.paths:
        p.error("give dump files, or --serve PORT --target HOST:PORT")
    return print_dumps(args)


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
