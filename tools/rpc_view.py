#!/usr/bin/env python
"""rpc_view — inspect a server or rpc_dump samples (reference
tools/rpc_view: a proxy server that forwards any path to the target's
builtin portal and annotates the rendering, rpc_view.cpp:23-60; plus the
dump-file printer role of rpc_replay's sibling tooling).

Two modes:

  Proxy a live server's portal (the reference tool's shape):
    python tools/rpc_view.py --serve 8888 --target 127.0.0.1:8000
    # then browse http://127.0.0.1:8888/status /vars /rpcz /protobufs ...

  Print rpc_dump sample files:
    python tools/rpc_view.py ./rpc_dump/requests.1234.0000
    python tools/rpc_view.py --service users --method get --json dump.0000

  Scrape /brpc_metrics and pretty-print the delta between two scrapes
  (the poor man's rpc_press dashboard — counters as rates, gauges and
  summary quantiles as current values):
    python tools/rpc_view.py --metrics --target 127.0.0.1:8000
    python tools/rpc_view.py --metrics --target 127.0.0.1:8000 \
        --interval 5 --prefix method_

  Scrape /rpcz?json=1 — recent sampled spans, or one assembled trace
  tree (the scrape-side twin of --metrics for the tracing plane):
    python tools/rpc_view.py --rpcz --target 127.0.0.1:8000
    python tools/rpc_view.py --rpcz --target 127.0.0.1:8000 \
        --trace-id 1f00dbeef --min-latency-us 500 --error-only

  Assemble ONE distributed trace across a fleet (pulls
  /rpcz?trace_id=&json=1 from every node, merges by span id, renders
  the cross-process parent→child tree):
    python tools/rpc_view.py --trace 1f00dbeef \
        --targets 10.0.0.1:8000,10.0.0.2:8000
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})?) (-?\d+(?:\.\d+)?"
    r"(?:[eE][+-]?\d+)?|[+-]Inf|NaN)$"
)


def parse_exposition(text: str):
    """Prometheus text exposition -> ({series_key: float}, {name: type}).
    A series key is the metric name plus its label set verbatim
    (``m{quantile="0.99"}``); types come from the ``# TYPE`` comments."""
    values = {}
    types = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) == 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        values[m.group(1)] = float(
            m.group(2).replace("Inf", "inf").replace("NaN", "nan")
        )
    return values, types


def _series_base(key: str) -> str:
    return key.partition("{")[0]


def _is_counterish(key: str, types: dict) -> bool:
    """counter samples and summary _sum/_count accumulate: show as rates."""
    base = _series_base(key)
    if types.get(base) == "counter":
        return True
    for suffix in ("_sum", "_count"):
        if base.endswith(suffix) and types.get(base[: -len(suffix)]) == "summary":
            return True
    return False


def metrics_delta_lines(before, after, types, seconds: float):
    """Human-readable rows for every series whose value changed between
    two scrapes (counter-ish series get a +delta and a per-second rate),
    plus quantile lines of any summary that saw traffic."""
    out = []
    changed_summaries = set()
    for key in sorted(after):
        base = _series_base(key)
        if types.get(base) == "summary":
            continue  # quantile lines: shown with their summary below
        old = before.get(key)
        new = after[key]
        if old is not None and old == new:
            continue
        if _is_counterish(key, types):
            delta = new - (old or 0.0)
            rate = delta / seconds if seconds > 0 else 0.0
            out.append(
                f"{key} {_num(old)} -> {_num(new)}  (+{_num(delta)}, "
                f"{rate:.1f}/s)"
            )
            if base.endswith("_count"):
                changed_summaries.add(base[: -len("_count")])
        else:
            out.append(f"{key} {_num(old)} -> {_num(new)}")
    for key in sorted(after):
        base = _series_base(key)
        if "{" in key and base in changed_summaries:
            out.append(f"{key} {_num(after[key])}")
    return out


def _num(v) -> str:
    if v is None:
        return "-"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))  # full precision: %g would round big counters
    return f"{v:g}"


def scrape_metrics(target: str, prefix: str = ""):
    """One GET /brpc_metrics against host:port -> (values, types)."""
    from incubator_brpc_tpu.protocol.http import http_call

    host, _, port = target.rpartition(":")
    path = "/brpc_metrics" + (f"?prefix={prefix}" if prefix else "")
    status, _, body = http_call(host, int(port), path, timeout=15)
    if status != 200:
        raise OSError(f"GET {path} -> {status}")
    return parse_exposition(body.decode())


def metrics_mode(target: str, interval: float, prefix: str = "") -> int:
    host, _, port = target.rpartition(":")
    if not host or not port.isdigit():
        print(f"bad --target {target!r} (want host:port)", file=sys.stderr)
        return 2
    try:
        before, types = scrape_metrics(target, prefix)
    except OSError as e:
        print(f"rpc_view: target {target} unreachable: {e}", file=sys.stderr)
        return 1
    if interval <= 0:
        # single scrape: dump current values
        for key in sorted(before):
            print(f"{key} {_num(before[key])}")
        print(f"# {len(before)} series from {target}")
        return 0
    t0 = time.monotonic()
    time.sleep(interval)
    try:
        after, types2 = scrape_metrics(target, prefix)
    except OSError as e:
        print(
            f"rpc_view: target {target} unreachable on second scrape: {e}",
            file=sys.stderr,
        )
        return 1
    # rates use the MEASURED window: the second scrape itself can take
    # long enough (loaded server, big percentile reservoirs) to skew
    # nominal-interval rates exactly when an operator is reading them
    elapsed = time.monotonic() - t0
    types.update(types2)
    lines = metrics_delta_lines(before, after, types, elapsed)
    print(f"# /brpc_metrics delta over {elapsed:.1f}s — {target}")
    for line in lines:
        print(line)
    print(f"# {len(after)} series, {len(lines)} rows changed")
    return 0


_LINK_SERIES_RE = re.compile(r"^device_link_(\d+)_([a-z0-9_]+?)(\{.*\})?$")


def links_table(values: dict) -> list:
    """Rows for every device link in one /brpc_metrics scrape: the
    per-link rtt/bytes-per-second recorders (PR 1) grouped by link id —
    the scrape-side rendering of ``DeviceLinkMap.link_profile()``, so an
    operator can see the same speeds the topology-aware session
    scheduler orders by."""
    links: dict = {}
    for key, val in values.items():
        m = _LINK_SERIES_RE.match(key)
        if m is None:
            continue
        link_id, field, label = int(m.group(1)), m.group(2), m.group(3)
        row = links.setdefault(link_id, {})
        if field == "step_rtt_us" and label == '{quantile="0.99"}':
            row["rtt_p99_us"] = val
        elif field == "step_rtt_us_sum":
            row["rtt_sum"] = val
        elif field == "step_rtt_us_count":
            row["steps"] = val
        elif field == "out_bytes_second" and not label:
            row["out_bps"] = val
        elif field == "in_bytes_second" and not label:
            row["in_bps"] = val
    out = []
    for link_id in sorted(links):
        row = links[link_id]
        steps = row.get("steps", 0.0)
        rtt = (row.get("rtt_sum", 0.0) / steps) if steps else 0.0
        out_bps = row.get("out_bps", 0.0)
        in_bps = row.get("in_bps", 0.0)
        out.append(
            f"device_link_{link_id}: rtt={rtt:.1f}us "
            f"p99={row.get('rtt_p99_us', 0.0):.1f}us "
            f"steps={int(steps)} out={out_bps:.0f}B/s in={in_bps:.0f}B/s "
            f"gbps={(out_bps + in_bps) / 1e9:.6f}"
        )
    return out


def links_mode(target: str) -> int:
    """Print the target's per-device-link telemetry (rtt + bytes/s per
    direction + GB/s) — the measured speeds the scheduler uses."""
    host, _, port = target.rpartition(":")
    if not host or not port.isdigit():
        print(f"bad --target {target!r} (want host:port)", file=sys.stderr)
        return 2
    try:
        values, _types = scrape_metrics(target, prefix="device_link_")
    except OSError as e:
        print(f"rpc_view: target {target} unreachable: {e}", file=sys.stderr)
        return 1
    lines = links_table(values)
    print(f"# device links of {target} — {len(lines)} live")
    for line in lines:
        print(line)
    if not lines:
        print("# (no per-link series: no live device links, or all retired)")
    return 0


def scrape_rpcz(
    target: str,
    trace_id: str = "",
    min_latency_us: float = None,
    error_only: bool = False,
):
    """GET /rpcz?json=1 against host:port -> list of Span objects."""
    from urllib.parse import urlencode

    from incubator_brpc_tpu.builtin.rpcz import span_from_dict
    from incubator_brpc_tpu.protocol.http import http_call

    host, _, port = target.rpartition(":")
    query = [("json", "1")]
    if trace_id:
        query.append(("trace_id", trace_id))
    if min_latency_us is not None:
        # urlencode, not f-strings: %g renders 1e6 as "1e+06" and a bare
        # '+' decodes to a space on the server side
        query.append(("min_latency_us", f"{min_latency_us:g}"))
    if error_only:
        query.append(("error_only", "1"))
    path = "/rpcz?" + urlencode(query)
    status, _, body = http_call(host, int(port), path, timeout=15)
    if status != 200:
        raise OSError(f"GET {path} -> {status}: {body[:200].decode(errors='replace')}")
    return [
        sp
        for sp in (span_from_dict(d) for d in json.loads(body.decode()))
        if sp is not None
    ]


def rpcz_mode(
    target: str,
    trace_id: str = "",
    min_latency_us: float = None,
    error_only: bool = False,
) -> int:
    """Print a target's recent sampled spans (or one assembled trace as
    an indented parent→child tree when --trace-id is given).  A trace
    carrying overlap-session chunk spans (``chunk=j/C`` annotations)
    additionally gets the overlap report — per-chunk ack-vs-next-compute
    timing with an OVERLAPPED/SERIALIZED verdict, so a schedule that
    regressed to serialization is visible at a glance."""
    from incubator_brpc_tpu.builtin.rpcz import (
        overlap_report,
        render_trace_tree,
        span_line,
    )

    host, _, port = target.rpartition(":")
    if not host or not port.isdigit():
        print(f"bad --target {target!r} (want host:port)", file=sys.stderr)
        return 2
    try:
        spans = scrape_rpcz(target, trace_id, min_latency_us, error_only)
    except (OSError, ValueError) as e:
        # carries the server's reason too (e.g. the 503 "rpcz is off" body)
        print(f"rpc_view: rpcz scrape of {target} failed: {e}", file=sys.stderr)
        return 1
    if trace_id and min_latency_us is None and not error_only:
        lines = render_trace_tree(spans) + overlap_report(spans)
    else:
        lines = [span_line(sp) for sp in spans]
    print(f"# /rpcz of {target} — {len(spans)} spans")
    for line in lines:
        print(line)
    return 0


def fleet_trace_mode(targets: list, trace_id: str) -> int:
    """Assemble ONE distributed trace from many processes: pull
    ``/rpcz?trace_id=<id>&json=1`` from every target and render the
    merged cross-process parent→child tree (plus the overlap report when
    the trace carries collective chunk spans).

    Span identity is the 63-bit span id, so parent links stitch across
    process boundaries exactly; clock skew between nodes follows the
    overlap verdict's discipline — parent→child EDGES come from ids,
    never clocks, and start-time ordering among siblings from different
    nodes is best-effort (each span keeps its producer's wall clock).
    Spans are tagged ``node=<target>`` so the origin of every line is
    visible in the merged rendering."""
    from incubator_brpc_tpu.builtin.rpcz import (
        overlap_report,
        render_trace_tree,
    )

    merged = {}
    counts = []
    failures = 0
    for target in targets:
        host, _, port = target.rpartition(":")
        if not host or not port.isdigit():
            print(f"bad target {target!r} (want host:port)", file=sys.stderr)
            return 2
        try:
            spans = scrape_rpcz(target, trace_id)
        except (OSError, ValueError) as e:
            print(
                f"rpc_view: rpcz scrape of {target} failed: {e}",
                file=sys.stderr,
            )
            failures += 1
            counts.append((target, -1))
            continue
        counts.append((target, len(spans)))
        for sp in spans:
            sp.annotations.append((0.0, f"node={target}"))
            # first pull wins on a duplicate span id (a node scraped
            # twice, or a persisted+live copy): the tree must not show
            # the same span as two children
            merged.setdefault(sp.span_id, sp)
    spans = sorted(merged.values(), key=lambda s: s.start_real_us)
    print(
        f"# trace {trace_id} across {len(targets)} nodes — "
        f"{len(spans)} spans"
    )
    for target, n in counts:
        print(f"#   {target}: " + ("unreachable" if n < 0 else f"{n} spans"))
    for line in render_trace_tree(spans) + overlap_report(spans):
        print(line)
    if failures == len(targets):
        return 1
    return 0


def make_proxy_server(target: str):
    """Build (but do not start) the rpc_view front server: every path
    relays to the target's portal, renderings are tagged with the origin
    (rpc_view.cpp:52-60). Returns the Server, or None on a bad target."""
    from incubator_brpc_tpu.protocol.http import http_call
    from incubator_brpc_tpu.rpc import Server, ServerOptions

    host, _, tport = target.rpartition(":")
    if not host or not tport.isdigit():
        return None

    def relay(frame):
        from urllib.parse import urlencode

        path = frame.path
        if frame.query:
            # values arrived URL-decoded (parse_qsl): re-encode, or spaces
            # and '&'/'=' inside values would corrupt the target's request
            path = f"{path}?{urlencode(frame.query)}"
        try:
            status, headers, body = http_call(
                host, int(tport), path, method=frame.method,
                body=frame.body if isinstance(frame.body, bytes) else b"",
                timeout=15,
            )
        except OSError as e:
            return 502, "text/plain", (
                f"rpc_view: target {target} unreachable: {e}\n".encode()
            )
        ctype = headers.get("content-type", "text/plain")
        # visually tag HUMAN renderings with the target (rpc_view.cpp:52-60)
        # — never binary or machine-parsed payloads (/dir files, pprof
        # folded output), which must relay byte-identical
        if "html" in ctype and b"</body>" in body:
            tag = f"<hr><i>rpc_view of {target}</i>".encode()
            body = body.replace(b"</body>", tag + b"</body>", 1)
        elif ctype.startswith("text/plain") and not path.startswith("/pprof"):
            body = f"# rpc_view of {target}{path}\n".encode() + body
        return status, ctype, body

    # no builtin pages on the front: the whole point is viewing the
    # TARGET's portal, so every path — /status, /vars, /rpcz — relays
    srv = Server(ServerOptions(has_builtin_services=False))
    srv.add_http_handler("/", relay)  # prefix: every path relays
    return srv


def serve_proxy(port: int, target: str) -> int:
    srv = make_proxy_server(target)
    if srv is None:
        print(f"bad --target {target!r} (want host:port)", file=sys.stderr)
        return 2
    if not srv.start(port):
        print(f"cannot listen on {port}", file=sys.stderr)
        return 1
    print(f"rpc_view of {target} on http://127.0.0.1:{srv.port}/  (Ctrl-C stops)")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def print_dumps(args) -> int:
    from incubator_brpc_tpu.rpc.dump import load_dump_file

    n = shown = 0
    for path in args.paths:
        for meta, payload, attachment in load_dump_file(path):
            n += 1
            if args.service and meta.service != args.service:
                continue
            if args.method and meta.method != args.method:
                continue
            shown += 1
            if args.json:
                print(
                    json.dumps(
                        {
                            "service": meta.service,
                            "method": meta.method,
                            "payload_len": len(payload),
                            "attachment_len": len(attachment),
                            "compress": meta.compress,
                            "log_id": meta.log_id,
                            "payload_head": payload[: args.max_payload].hex(),
                        }
                    )
                )
            else:
                preview = payload[: args.max_payload]
                print(
                    f"[{shown - 1}] {meta.service}.{meta.method} "
                    f"payload={len(payload)}B attachment={len(attachment)}B "
                    f"compress={meta.compress or '-'} log_id={meta.log_id} "
                    f"| {preview!r}"
                )
    print(f"{shown}/{n} samples", file=sys.stderr if args.json else sys.stdout)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="*", help="dump files (dump mode)")
    p.add_argument("--max-payload", type=int, default=64, help="bytes shown")
    p.add_argument("--service", help="only samples of this service")
    p.add_argument("--method", help="only samples of this method")
    p.add_argument("--json", action="store_true", help="one JSON line per sample")
    p.add_argument("--serve", type=int, help="proxy mode: listen on this port")
    p.add_argument(
        "--target", help="proxy/metrics mode: host:port of the server"
    )
    p.add_argument(
        "--metrics",
        action="store_true",
        help="scrape /brpc_metrics from --target and print the delta "
        "between two scrapes (--interval apart; 0 = one scrape)",
    )
    p.add_argument(
        "--interval",
        type=float,
        default=1.0,
        help="metrics mode: seconds between the two scrapes",
    )
    p.add_argument(
        "--prefix", default="", help="metrics mode: only metrics with this prefix"
    )
    p.add_argument(
        "--rpcz",
        action="store_true",
        help="scrape /rpcz?json=1 from --target and print recent spans "
        "(or one trace tree with --trace-id)",
    )
    p.add_argument(
        "--links",
        action="store_true",
        help="scrape --target's per-device-link telemetry (rtt + bytes/s "
        "+ GB/s per link — what the topology-aware scheduler orders by)",
    )
    p.add_argument(
        "--trace-id",
        default="",
        help="rpcz mode: assemble and print this trace (hex) as a tree",
    )
    p.add_argument(
        "--trace",
        default="",
        help="fleet mode: the (hex) trace id to assemble across --targets",
    )
    p.add_argument(
        "--targets",
        default="",
        help="fleet trace assembly: comma-separated host:port list — pull "
        "/rpcz?trace_id=&json=1 from every node and render the merged "
        "cross-process tree (rpc_view --trace <id> --targets a:p,b:p)",
    )
    p.add_argument(
        "--min-latency-us",
        type=float,
        default=None,
        help="rpcz mode: only spans at least this slow (latency-ordered)",
    )
    p.add_argument(
        "--error-only",
        action="store_true",
        help="rpcz mode: only spans that ended in an error",
    )
    args = p.parse_args(argv)

    if args.targets:
        trace = args.trace or args.trace_id
        if not trace:
            p.error("--targets requires --trace <hex trace id>")
        return fleet_trace_mode(
            [t for t in args.targets.split(",") if t], trace
        )
    if args.links:
        if not args.target:
            p.error("--links requires --target host:port")
        return links_mode(args.target)
    if args.rpcz:
        if not args.target:
            p.error("--rpcz requires --target host:port")
        return rpcz_mode(
            args.target, args.trace_id, args.min_latency_us, args.error_only
        )
    if args.metrics:
        if not args.target:
            p.error("--metrics requires --target host:port")
        return metrics_mode(args.target, args.interval, args.prefix)
    if args.serve is not None:
        if not args.target:
            p.error("--serve requires --target host:port")
        return serve_proxy(args.serve, args.target)
    if not args.paths:
        p.error("give dump files, or --serve/--metrics with --target")
    return print_dumps(args)


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
