// tbnet — native network plane implementation.  See tbnet.h for the role
// and the reference seams this re-designs (event_dispatcher.cpp,
// input_messenger.cpp:60-129, socket.cpp:1591-1686, baidu_rpc_protocol.cpp).
//
// Threading model: N epoll loop threads own connections (a connection is
// read by exactly its loop thread; LT events, no oneshot re-arm needed).
// Foreign threads (Python handlers answering asynchronously, the client's
// writers) touch a connection only through versioned tokens resolved out
// of a tb_respool — the same Address-after-SetFailed discipline the
// reference builds on Socket's versioned refs (socket.h:619-630).  Writes
// from any thread serialize on the connection's write mutex; the fd is
// closed only after every in-flight token holder drops its ref.

#include "tbnet.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <zlib.h>  // crc32: the dispatch key's second polynomial

namespace {

// wire constants — must match protocol/tbus_std.py and tbutil.cc
constexpr uint32_t kMagic = 0x54505243;  // "TPRC"
constexpr uint32_t kFlagResponse = 1;
constexpr uint32_t kFlagStream = 2;
constexpr uint32_t kFlagHasMeta = 4;
constexpr uint32_t kFlagBodyCrc = 8;
constexpr size_t kHeader = 32;

constexpr int kKindEcho = 1;
constexpr int kKindNop = 2;
constexpr int kKindCallback = 3;  // user C fn: tb_server_register_native_fn

uint64_t now_ms() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::milliseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

// ---------------------------------------------------------------------------
// minimal JSON scanner for the flat meta object.  The native plane needs
// only the routing fields (service/method/attachment_size); any meta it
// cannot fully vouch for (escapes, compression, stream/trace fields, parse
// trouble) routes to the Python frame callback, which parses properly.
// ---------------------------------------------------------------------------

struct MetaLite {
  bool ok = false;         // meta parsed cleanly
  bool to_python = false;  // fields beyond the native fast path's scope
  std::string service;
  std::string method;
  long attachment = 0;
};

struct Scan {
  const char* p;
  const char* end;
  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool lit(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return false;
  }
  // raw string body between quotes; *escaped set if any backslash seen
  bool str(std::string* out, bool* escaped) {
    ws();
    if (p >= end || *p != '"') return false;
    ++p;
    const char* s = p;
    bool esc = false;
    while (p < end) {
      if (*p == '\\') {
        esc = true;
        p += 2;
        continue;
      }
      if (*p == '"') {
        if (out) out->assign(s, p - s);
        if (escaped) *escaped = esc;
        ++p;
        return true;
      }
      ++p;
    }
    return false;
  }
  bool skip_value();
  bool skip_container(char open, char close) {
    int depth = 1;
    ++p;  // past open
    while (p < end && depth > 0) {
      if (*p == '"') {
        if (!str(nullptr, nullptr)) return false;
        continue;
      }
      if (*p == open) ++depth;
      if (*p == close) --depth;
      ++p;
    }
    return depth == 0;
  }
};

bool Scan::skip_value() {
  ws();
  if (p >= end) return false;
  char c = *p;
  if (c == '"') return str(nullptr, nullptr);
  if (c == '{') return skip_container('{', '}');
  if (c == '[') return skip_container('[', ']');
  const char* s = p;  // number / true / false / null
  while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
         *p != '\t' && *p != '\n' && *p != '\r')
    ++p;
  return p > s;
}

MetaLite scan_meta(const char* s, size_t n) {
  MetaLite m;
  if (n == 0) {
    m.ok = true;
    return m;
  }
  Scan sc{s, s + n};
  if (!sc.lit('{')) return m;
  sc.ws();
  if (sc.p < sc.end && *sc.p == '}') {
    m.ok = true;
    return m;
  }
  for (;;) {
    std::string key;
    bool kesc = false;
    if (!sc.str(&key, &kesc) || kesc) return m;
    if (!sc.lit(':')) return m;
    if (key == "service" || key == "method") {
      std::string v;
      bool vesc = false;
      if (!sc.str(&v, &vesc)) return m;
      if (vesc) m.to_python = true;  // escaped name: Python unescapes
      (key == "service" ? m.service : m.method) = std::move(v);
    } else if (key == "attachment_size") {
      sc.ws();
      char* endp = nullptr;
      m.attachment = strtol(sc.p, &endp, 10);
      if (endp == sc.p || m.attachment < 0) return m;
      sc.p = endp;
    } else {
      // compress, stream ids, trace ids, error_text, extra...: semantics
      // the native fast path doesn't implement — Python handles them
      if (!sc.skip_value()) return m;
      m.to_python = true;
    }
    sc.ws();
    if (sc.p < sc.end && *sc.p == ',') {
      ++sc.p;
      continue;
    }
    if (sc.lit('}')) break;
    return m;
  }
  m.ok = true;
  return m;
}

// ---------------------------------------------------------------------------
// frame pack helpers
// ---------------------------------------------------------------------------

// append the 32-byte header (+ small meta) contiguously
void append_header(tb_iobuf* out, const void* meta, size_t meta_len,
                   size_t body_rest_len, uint32_t crc, uint32_t cid_lo,
                   uint32_t cid_hi, uint32_t flags, uint32_t error_code) {
  uint32_t h[8];
  h[0] = kMagic;
  h[1] = static_cast<uint32_t>(meta_len + body_rest_len);
  h[2] = flags;
  h[3] = cid_lo;
  h[4] = cid_hi;
  h[5] = static_cast<uint32_t>(meta_len);
  h[6] = crc;
  h[7] = error_code;
  if (meta_len > 0 && meta_len <= 4096) {
    char scratch[4096 + sizeof h];
    memcpy(scratch, h, sizeof h);
    memcpy(scratch + sizeof h, meta, meta_len);
    tb_iobuf_append(out, scratch, sizeof h + meta_len);
  } else {
    tb_iobuf_append(out, h, sizeof h);
    if (meta_len) tb_iobuf_append(out, meta, meta_len);
  }
}

// whole frame from contiguous caller memory
void pack_flat(tb_iobuf* out, const void* meta, size_t meta_len,
               const void* payload, size_t payload_len, const void* att,
               size_t att_len, uint32_t cid_lo, uint32_t cid_hi,
               uint32_t flags, uint32_t error_code) {
  if (meta_len) flags |= kFlagHasMeta;
  uint32_t crc = tb_crc32c(0, meta, meta_len);
  if (flags & kFlagBodyCrc) {
    crc = tb_crc32c(crc, payload, payload_len);
    crc = tb_crc32c(crc, att, att_len);
  }
  append_header(out, meta, meta_len, payload_len + att_len, crc, cid_lo,
                cid_hi, flags, error_code);
  if (payload_len) tb_iobuf_append(out, payload, payload_len);
  if (att_len) tb_iobuf_append(out, att, att_len);
}

// ---------------------------------------------------------------------------
// connection registry (token = versioned respool id; global resolve mutex +
// per-conn refcount gate the fd against cross-thread teardown)
// ---------------------------------------------------------------------------

struct NetLoop;

struct PollObj {
  int kind;  // 0 conn, 1 listener, 2 wake
  explicit PollObj(int k) : kind(k) {}
  virtual ~PollObj() = default;
};

struct NetConn : PollObj {
  NetConn() : PollObj(0) {}
  int fd = -1;
  uint64_t token = 0;
  NetLoop* loop = nullptr;
  tb_server* srv = nullptr;
  tb_iobuf* rbuf = nullptr;
  tb_iobuf* wbuf = nullptr;
  std::mutex wmu;
  bool want_out = false;
  bool sniffed = false;
  // one-entry meta memo: a client pumping one method sends byte-identical
  // meta every frame — remember the resolved native method for those exact
  // bytes and skip the JSON scan + name join + flatmap probe (the
  // preferred-protocol-memory idea applied to routing)
  std::string memo_meta;
  uint64_t memo_idx = 0;
  long memo_attachment = -1;  // -1 = no memo
  std::atomic<bool> dead{false};
  std::atomic<int> refs{0};
};

std::mutex g_conn_mu;
tb_respool* g_conn_pool = nullptr;  // slots hold NetConn*

uint64_t conn_register(NetConn* c) {
  std::lock_guard<std::mutex> g(g_conn_mu);
  if (g_conn_pool == nullptr) g_conn_pool = tb_respool_create(sizeof(void*));
  uint64_t id = 0;
  void* slot = tb_respool_get(g_conn_pool, &id);
  *static_cast<NetConn**>(slot) = c;
  c->token = id;
  return id;
}

NetConn* conn_resolve(uint64_t token) {
  std::lock_guard<std::mutex> g(g_conn_mu);
  if (g_conn_pool == nullptr) return nullptr;
  void* slot = tb_respool_address(g_conn_pool, token);
  if (slot == nullptr) return nullptr;
  NetConn* c = *static_cast<NetConn**>(slot);
  if (c == nullptr || c->dead.load(std::memory_order_acquire)) return nullptr;
  c->refs.fetch_add(1, std::memory_order_acq_rel);
  return c;
}

void conn_unref(NetConn* c) { c->refs.fetch_sub(1, std::memory_order_acq_rel); }

// retire the token and wait out foreign holders; afterwards the caller owns
// the conn exclusively (the deferred-close discipline of sock.py _io_refs)
void conn_retire(NetConn* c) {
  {
    std::lock_guard<std::mutex> g(g_conn_mu);
    c->dead.store(true, std::memory_order_release);
    tb_respool_return(g_conn_pool, c->token);
  }
  while (c->refs.load(std::memory_order_acquire) > 0) usleep(50);
}

// ---------------------------------------------------------------------------
// server structures
// ---------------------------------------------------------------------------

struct Wake : PollObj {
  Wake() : PollObj(2) {}
  int fd = -1;
};

struct NetLoop {
  int epfd = -1;
  Wake wake;
  std::thread th;
  std::atomic<bool> stopping{false};
  std::vector<NetConn*> conns;
  std::mutex conns_mu;  // guards conns (loop thread + stop-time sweep)
};

struct NativeMethod {
  int kind;
  // runtime-retunable (tb_server_set_native_max_concurrency stores from
  // the application thread while loop threads load per request)
  std::atomic<uint32_t> max_concurrency{0};
  std::atomic<uint32_t> nprocessing{0};
  std::atomic<uint64_t> nreq{0};
  std::atomic<uint64_t> nerr{0};
  std::string full_name;
  tb_native_fn fn = nullptr;  // kKindCallback
  void* ud = nullptr;
};

struct Listener : PollObj {
  Listener() : PollObj(1) {}
  int fd = -1;
};

struct ErrorCodes {
  // mirrors utils/status.py ErrorCode (the cross-plane error constants)
  uint32_t enomethod = 1002;
  uint32_t elimit = 2004;
  uint32_t erequest = 1003;
};

}  // namespace

struct tb_server {
  std::vector<NetLoop*> loops;
  Listener listener;
  int port = 0;
  std::atomic<size_t> next_loop{0};
  tb_frame_fn frame_cb = nullptr;
  void* frame_ctx = nullptr;
  tb_handoff_fn handoff_cb = nullptr;
  void* handoff_ctx = nullptr;
  tb_closed_fn closed_cb = nullptr;
  void* closed_ctx = nullptr;
  size_t max_body = 512u << 20;
  ErrorCodes errs;
  tb_flatmap* methods = nullptr;  // key -> index into native_methods
  std::vector<NativeMethod*> native_methods;
  std::atomic<uint64_t> accepted{0};
  std::atomic<uint64_t> native_reqs{0};
  std::atomic<uint64_t> cb_frames{0};
  std::atomic<uint64_t> handoffs{0};
  std::atomic<uint64_t> live_conns{0};
  std::atomic<bool> stopped{false};
};

namespace {

uint64_t method_key(const char* name, size_t n) {
  uint64_t lo = tb_crc32c(0, name, n);
  uint64_t hi =
      crc32(0, reinterpret_cast<const Bytef*>(name), static_cast<uInt>(n));
  return lo | (hi << 32);
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
}

// ---- write path (per-conn mutex; any thread) ----

// under c->wmu: drain wbuf to the fd, arming/disarming EPOLLOUT
void conn_flush_locked(NetConn* c) {
  while (tb_iobuf_size(c->wbuf) > 0) {
    long rc = tb_iobuf_cut_into_fd(c->wbuf, c->fd, 4u << 20);
    if (rc > 0) continue;
    if (rc == -EINTR) continue;
    if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) {
      if (!c->want_out) {
        c->want_out = true;
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.ptr = static_cast<PollObj*>(c);
        epoll_ctl(c->loop->epfd, EPOLL_CTL_MOD, c->fd, &ev);
      }
      return;
    }
    // hard error: shutdown so the loop thread reaps via EPOLLHUP
    shutdown(c->fd, SHUT_RDWR);
    return;
  }
  if (c->want_out) {
    c->want_out = false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(c);
    epoll_ctl(c->loop->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

void conn_queue_iobuf(NetConn* c, const tb_iobuf* data) {
  std::lock_guard<std::mutex> g(c->wmu);
  tb_iobuf_append_iobuf(c->wbuf, data);
  conn_flush_locked(c);
}

// loop-thread-only teardown; fd closes only after foreign refs drain
void conn_destroy(NetConn* c, bool close_fd) {
  epoll_ctl(c->loop->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  uint64_t token = c->token;
  conn_retire(c);
  if (close_fd && c->fd >= 0) close(c->fd);
  if (c->srv) c->srv->live_conns.fetch_sub(1);
  // close_fd==false means handoff: the connection lives on in Python
  if (close_fd && c->srv && c->srv->closed_cb != nullptr)
    c->srv->closed_cb(c->srv->closed_ctx, token);
  {
    std::lock_guard<std::mutex> g(c->loop->conns_mu);
    auto& v = c->loop->conns;
    for (size_t i = 0; i < v.size(); ++i)
      if (v[i] == c) {
        v[i] = v.back();
        v.pop_back();
        break;
      }
  }
  tb_iobuf_destroy(c->rbuf);
  tb_iobuf_destroy(c->wbuf);
  delete c;
}

// ---- server-side frame dispatch ----

// append an error response frame into `out` (flushed with the batch)
void append_error(tb_iobuf* out, uint32_t cid_lo, uint32_t cid_hi,
                  uint32_t code, const char* text) {
  char meta[256];
  int n = snprintf(meta, sizeof meta, "{\"error_text\":\"%s\"}", text);
  if (n < 0) n = 0;
  pack_flat(out, meta, static_cast<size_t>(n), nullptr, 0, nullptr, 0, cid_lo,
            cid_hi, kFlagResponse, code);
}

// Native method kinds: the response is built and appended into the burst's
// batch without ever leaving C++ — the whole ProcessRpcRequest/user code/
// SendRpcResponse round (baidu_rpc_protocol.cpp:307-503,136) for these
// methods is native.  `out` collects every response of one readable burst;
// the caller queues it once (one writev per burst, not per request).
// `body` stays owned by the caller (a per-burst reusable scratch —
// creating/destroying an iobuf handle per request was measurable on the
// pump's ns/req floor); echo ref-shares its blocks into `out` before the
// caller clears it.
void run_native(NetConn* c, NativeMethod* nm, const tb_tbus_hdr* hdr,
                const MetaLite& ml, tb_iobuf* body, tb_iobuf* out) {
  nm->nreq.fetch_add(1, std::memory_order_relaxed);
  c->srv->native_reqs.fetch_add(1, std::memory_order_relaxed);
  // snapshot ONCE: a runtime retune between the admission fetch_add and
  // the completion fetch_sub must see a consistent gate, or the counter
  // leaks (limit dropped to 0 mid-request) / underflows (raised from 0)
  const uint32_t limit = nm->max_concurrency.load(std::memory_order_relaxed);
  if (limit && nm->nprocessing.fetch_add(1) >= limit) {
    nm->nprocessing.fetch_sub(1);
    nm->nerr.fetch_add(1, std::memory_order_relaxed);
    append_error(out, hdr->cid_lo, hdr->cid_hi, c->srv->errs.elimit,
                 "concurrency limit reached");
    return;  // caller owns body
  }
  uint32_t flags = kFlagResponse | (hdr->flags & kFlagBodyCrc);
  char meta[64];
  size_t meta_len = 0;
  if (nm->kind == kKindEcho) {
    if (ml.attachment > 0) {
      int n = snprintf(meta, sizeof meta, "{\"attachment_size\":%ld}",
                       ml.attachment);
      meta_len = n > 0 ? static_cast<size_t>(n) : 0;
    }
    if (meta_len) flags |= kFlagHasMeta;
    uint32_t crc = tb_crc32c(0, meta, meta_len);
    size_t blen = tb_iobuf_size(body);
    if (flags & kFlagBodyCrc) crc = tb_iobuf_crc32c(body, crc, 0, blen);
    append_header(out, meta, meta_len, blen, crc, hdr->cid_lo, hdr->cid_hi,
                  flags, 0);
    tb_iobuf_append_iobuf(out, body);  // zero-copy: request refs shared
  } else if (nm->kind == kKindCallback) {
    // contiguous request for the C ABI (stack buffer for small bodies)
    size_t blen = tb_iobuf_size(body);
    char stackbuf[4096];
    char* req = blen <= sizeof stackbuf ? stackbuf
                                        : static_cast<char*>(malloc(blen));
    if (req == nullptr) {  // OOM on a huge body: an error response, not a crash
      nm->nerr.fetch_add(1, std::memory_order_relaxed);
      append_error(out, hdr->cid_lo, hdr->cid_hi, c->srv->errs.erequest,
                   "request too large to stage");
      if (limit) nm->nprocessing.fetch_sub(1);
      return;  // caller owns body
    }
    if (blen) tb_iobuf_copy_to(body, req, blen, 0);
    char* resp = nullptr;
    size_t resp_len = 0;
    int rc = nm->fn(nm->ud, req, blen, &resp, &resp_len);
    if (req != stackbuf) free(req);
    if (rc != 0) {
      nm->nerr.fetch_add(1, std::memory_order_relaxed);
      append_error(out, hdr->cid_lo, hdr->cid_hi, static_cast<uint32_t>(rc),
                   "native method failed");
    } else {
      uint32_t crc = tb_crc32c(0, nullptr, 0);
      if (flags & kFlagBodyCrc) crc = tb_crc32c(crc, resp, resp_len);
      append_header(out, nullptr, 0, resp_len, crc, hdr->cid_lo, hdr->cid_hi,
                    flags, 0);
      if (resp_len) tb_iobuf_append(out, resp, resp_len);
    }
    free(resp);
  } else {  // nop
    append_header(out, nullptr, 0, 0, tb_crc32c(0, nullptr, 0), hdr->cid_lo,
                  hdr->cid_hi, flags, 0);
  }
  // body is the caller's reusable scratch: NOT destroyed here (the echo
  // kind ref-shared its blocks into `out`; clear just drops this handle)
  if (limit) nm->nprocessing.fetch_sub(1);
}

enum class FrameStatus { kOk, kHandoff, kKilled };

void do_handoff(NetConn* c) {
  tb_server* s = c->srv;
  s->handoffs.fetch_add(1, std::memory_order_relaxed);
  size_t n = tb_iobuf_size(c->rbuf);
  char* buffered = static_cast<char*>(malloc(n ? n : 1));
  if (n) tb_iobuf_copy_to(c->rbuf, buffered, n, 0);
  int fd = c->fd;
  tb_handoff_fn cb = s->handoff_cb;
  void* ctx = s->handoff_ctx;
  conn_destroy(c, /*close_fd=*/false);
  if (cb != nullptr) {
    cb(ctx, fd, buffered, n);  // callee owns fd from here
  } else {
    close(fd);
  }
  free(buffered);
}

FrameStatus process_frames(NetConn* c) {
  tb_server* s = c->srv;
  if (!c->sniffed) {
    if (tb_iobuf_size(c->rbuf) < 4) return FrameStatus::kOk;
    uint32_t magic = 0;
    tb_iobuf_copy_to(c->rbuf, &magic, 4, 0);
    if (magic != kMagic) {
      do_handoff(c);
      return FrameStatus::kHandoff;
    }
    c->sniffed = true;
  }
  // One response batch per readable burst: native responses append here
  // and flush with ONE conn_queue_iobuf (one writev) at every exit —
  // the per-request syscall was the dominant cost of the old shape.
  tb_iobuf* batch = tb_iobuf_create();
  tb_iobuf* scratch = tb_iobuf_create();  // per-frame body, cleared and reused
  auto flush = [&](FrameStatus st) {
    // every exit flushes: even a killed connection sends the responses of
    // the frames that parsed cleanly before the bad one
    if (tb_iobuf_size(batch) > 0) conn_queue_iobuf(c, batch);
    tb_iobuf_destroy(batch);
    tb_iobuf_destroy(scratch);
    return st;
  };
  for (;;) {
    tb_tbus_hdr hdr;
    int rc = tb_tbus_peek(c->rbuf, &hdr);
    if (rc == 1) return flush(FrameStatus::kOk);
    if (rc == -1 || hdr.meta_len > hdr.body_len || hdr.body_len > s->max_body) {
      flush(FrameStatus::kKilled);  // earlier valid responses go out
      conn_destroy(c, true);
      return FrameStatus::kKilled;
    }
    if (tb_iobuf_size(c->rbuf) < kHeader + hdr.body_len)
      return flush(FrameStatus::kOk);
    char mstack[4096];
    std::string mheap;
    char* mptr = nullptr;
    if (hdr.meta_len > 0) {
      if (hdr.meta_len <= sizeof mstack) {
        mptr = mstack;
      } else {
        mheap.resize(hdr.meta_len);
        mptr = &mheap[0];
      }
    }
    rc = tb_tbus_cut(c->rbuf, &hdr, mptr, scratch);
    if (rc != 0) {  // crc mismatch / malformed: the stream can't re-sync
      flush(FrameStatus::kKilled);
      conn_destroy(c, true);
      return FrameStatus::kKilled;
    }
    const char* cb_meta = mptr != nullptr ? mptr : mstack;  // never null
    // native fast path: plain request frame whose meta is fully understood
    if ((hdr.flags & (kFlagResponse | kFlagStream)) == 0) {
      if (c->memo_attachment >= 0 && hdr.meta_len == c->memo_meta.size() &&
          memcmp(cb_meta, c->memo_meta.data(), hdr.meta_len) == 0 &&
          c->memo_attachment <= static_cast<long>(tb_iobuf_size(scratch))) {
        MetaLite ml;
        ml.attachment = c->memo_attachment;
        run_native(c, s->native_methods[c->memo_idx], &hdr, ml, scratch,
                   batch);
        tb_iobuf_clear(scratch);
        continue;
      }
      MetaLite ml = scan_meta(cb_meta, hdr.meta_len);
      if (ml.ok && !ml.to_python &&
          ml.attachment <= static_cast<long>(tb_iobuf_size(scratch))) {
        char full[256];
        size_t sl = ml.service.size(), mn = ml.method.size();
        if (sl + 1 + mn < sizeof full) {
          memcpy(full, ml.service.data(), sl);
          full[sl] = '.';
          memcpy(full + sl + 1, ml.method.data(), mn);
          size_t fn = sl + 1 + mn;
          full[fn] = '\0';
          uint64_t idx = 0;
          if (s->methods != nullptr &&
              tb_flatmap_get(s->methods, method_key(full, fn), &idx) == 1 &&
              s->native_methods[idx]->full_name == full) {
            c->memo_meta.assign(cb_meta, hdr.meta_len);
            c->memo_idx = idx;
            c->memo_attachment = ml.attachment;
            run_native(c, s->native_methods[idx], &hdr, ml, scratch, batch);
            tb_iobuf_clear(scratch);
            continue;
          }
        }
      }
    }
    // python route (responses, streams, compressed, unknown methods —
    // admission/stats/errors stay consistent with the Python server path)
    s->cb_frames.fetch_add(1, std::memory_order_relaxed);
    if (s->frame_cb == nullptr) {
      if ((hdr.flags & kFlagResponse) == 0)
        append_error(batch, hdr.cid_lo, hdr.cid_hi, s->errs.enomethod,
                     "no such method");
      tb_iobuf_clear(scratch);
      continue;
    }
    // the Python callee owns its body: hand it a fresh handle that
    // ref-shares the scratch's blocks (no byte copy), then reuse scratch
    tb_iobuf* body = tb_iobuf_create();
    tb_iobuf_append_iobuf(body, scratch);
    tb_iobuf_clear(scratch);
    s->frame_cb(s->frame_ctx, c->token, hdr.cid_lo, hdr.cid_hi, hdr.flags,
                hdr.error_code, cb_meta, hdr.meta_len, body);
  }
}

void conn_readable(NetConn* c) {
  size_t burst = tb_iobuf_read_burst();
  bool eof = false;
  for (;;) {
    long rc = tb_iobuf_append_from_fd(c->rbuf, c->fd, burst);
    if (rc > 0) {
      if (static_cast<size_t>(rc) < burst) break;
      continue;
    }
    if (rc == -EAGAIN || rc == -EWOULDBLOCK) break;
    if (rc == -EINTR) continue;
    eof = true;  // 0 = EOF; other negatives = read error
    break;
  }
  if (tb_iobuf_size(c->rbuf) > 0) {
    FrameStatus st = process_frames(c);
    if (st != FrameStatus::kOk) return;  // conn already gone
  }
  if (eof) conn_destroy(c, true);
}

void accept_ready(tb_server* s) {
  for (;;) {
    int fd = accept4(s->listener.fd, nullptr, nullptr,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) return;  // EAGAIN / EMFILE / EINTR: next event retries
    set_nodelay(fd);
    s->accepted.fetch_add(1, std::memory_order_relaxed);
    s->live_conns.fetch_add(1, std::memory_order_relaxed);
    NetConn* c = new NetConn();
    c->fd = fd;
    c->srv = s;
    c->loop = s->loops[s->next_loop.fetch_add(1) % s->loops.size()];
    c->rbuf = tb_iobuf_create();
    c->wbuf = tb_iobuf_create();
    conn_register(c);
    {
      std::lock_guard<std::mutex> g(c->loop->conns_mu);
      c->loop->conns.push_back(c);
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(c);
    if (epoll_ctl(c->loop->epfd, EPOLL_CTL_ADD, fd, &ev) != 0)
      conn_destroy(c, true);
  }
}

void loop_run(tb_server* s, NetLoop* l) {
  epoll_event evs[128];
  while (!l->stopping.load(std::memory_order_acquire)) {
    int n = epoll_wait(l->epfd, evs, 128, 500);
    for (int i = 0; i < n; ++i) {
      PollObj* o = static_cast<PollObj*>(evs[i].data.ptr);
      if (o == nullptr) continue;
      if (o->kind == 2) {  // wake
        uint64_t v;
        ssize_t r = read(static_cast<Wake*>(o)->fd, &v, sizeof v);
        (void)r;
        continue;
      }
      if (o->kind == 1) {  // listener
        accept_ready(s);
        continue;
      }
      NetConn* c = static_cast<NetConn*>(o);
      uint32_t e = evs[i].events;
      if (e & (EPOLLERR | EPOLLHUP)) {
        conn_destroy(c, true);
        continue;
      }
      if (e & EPOLLOUT) {
        std::lock_guard<std::mutex> g(c->wmu);
        conn_flush_locked(c);
      }
      if (e & EPOLLIN) conn_readable(c);
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// server C API
// ---------------------------------------------------------------------------

tb_server* tb_server_create(int nloops) {
  if (nloops < 1) nloops = 1;
  tb_server* s = new tb_server();
  s->methods = tb_flatmap_create(64);
  for (int i = 0; i < nloops; ++i) {
    NetLoop* l = new NetLoop();
    l->epfd = epoll_create1(EPOLL_CLOEXEC);
    l->wake.fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = static_cast<PollObj*>(&l->wake);
    epoll_ctl(l->epfd, EPOLL_CTL_ADD, l->wake.fd, &ev);
    s->loops.push_back(l);
  }
  return s;
}

void tb_server_set_frame_cb(tb_server* s, tb_frame_fn cb, void* ctx) {
  s->frame_cb = cb;
  s->frame_ctx = ctx;
}

void tb_server_set_handoff_cb(tb_server* s, tb_handoff_fn cb, void* ctx) {
  s->handoff_cb = cb;
  s->handoff_ctx = ctx;
}

void tb_server_set_closed_cb(tb_server* s, tb_closed_fn cb, void* ctx) {
  s->closed_cb = cb;
  s->closed_ctx = ctx;
}

void tb_server_set_max_body(tb_server* s, size_t bytes) { s->max_body = bytes; }

namespace {

int register_native_common(tb_server* s, const char* full_name, int kind,
                           tb_native_fn fn, void* ud,
                           uint32_t max_concurrency) {
  uint64_t key = method_key(full_name, strlen(full_name));
  uint64_t existing = 0;
  if (tb_flatmap_get(s->methods, key, &existing) == 1)
    return -1;  // double registration / key collision: keep the Python route
  NativeMethod* nm = new NativeMethod();
  nm->kind = kind;
  nm->fn = fn;
  nm->ud = ud;
  nm->max_concurrency.store(max_concurrency, std::memory_order_relaxed);
  nm->full_name = full_name;
  s->native_methods.push_back(nm);
  tb_flatmap_insert(s->methods, key, s->native_methods.size() - 1);
  return 0;
}

}  // namespace

int tb_server_set_native_max_concurrency(tb_server* s, const char* full_name,
                                         uint32_t max_concurrency) {
  // runtime retune of a natively-dispatched method's admission limit
  // (the Python plane's MaxConcurrencyOf setter must reach methods that
  // never touch the interpreter); nm->max_concurrency is read per
  // request, so the store takes effect on the next admission check
  for (NativeMethod* nm : s->native_methods) {
    if (nm->full_name == full_name) {
      nm->max_concurrency.store(max_concurrency, std::memory_order_relaxed);
      return 0;
    }
  }
  return -1;
}

long tb_server_get_native_max_concurrency(tb_server* s,
                                          const char* full_name) {
  for (NativeMethod* nm : s->native_methods) {
    if (nm->full_name == full_name)
      return static_cast<long>(
          nm->max_concurrency.load(std::memory_order_relaxed));
  }
  return -1;  // not natively registered
}

int tb_server_register_native(tb_server* s, const char* full_name, int kind,
                              uint32_t max_concurrency) {
  if (kind != kKindEcho && kind != kKindNop) return -1;
  return register_native_common(s, full_name, kind, nullptr, nullptr,
                                max_concurrency);
}

int tb_server_register_native_fn(tb_server* s, const char* full_name,
                                 tb_native_fn fn, void* ud,
                                 uint32_t max_concurrency) {
  if (fn == nullptr) return -1;
  return register_native_common(s, full_name, kKindCallback, fn, ud,
                                max_concurrency);
}

int tb_server_listen(tb_server* s, const char* ip, int port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0 ||
      listen(fd, 1024) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->listener.fd = fd;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = static_cast<PollObj*>(&s->listener);
  epoll_ctl(s->loops[0]->epfd, EPOLL_CTL_ADD, fd, &ev);
  for (NetLoop* l : s->loops) l->th = std::thread(loop_run, s, l);
  return s->port;
}

int tb_server_port(const tb_server* s) { return s->port; }

void tb_server_stop(tb_server* s) {
  if (s->stopped.exchange(true)) return;
  for (NetLoop* l : s->loops) {
    l->stopping.store(true, std::memory_order_release);
    uint64_t one = 1;
    ssize_t r = write(l->wake.fd, &one, sizeof one);
    (void)r;
  }
  for (NetLoop* l : s->loops)
    if (l->th.joinable()) l->th.join();
  if (s->listener.fd >= 0) {
    close(s->listener.fd);
    s->listener.fd = -1;
  }
  // loops are quiescent: sweep remaining conns single-threaded
  for (NetLoop* l : s->loops) {
    std::vector<NetConn*> left;
    {
      std::lock_guard<std::mutex> g(l->conns_mu);
      left = l->conns;
    }
    for (NetConn* c : left) conn_destroy(c, true);
  }
}

void tb_server_destroy(tb_server* s) {
  tb_server_stop(s);
  for (NetLoop* l : s->loops) {
    close(l->wake.fd);
    close(l->epfd);
    delete l;
  }
  for (NativeMethod* nm : s->native_methods) delete nm;
  tb_flatmap_destroy(s->methods);
  delete s;
}

void tb_server_stats(const tb_server* s, uint64_t* accepted,
                     uint64_t* native_reqs, uint64_t* cb_frames,
                     uint64_t* handoffs, uint64_t* live_conns) {
  if (accepted) *accepted = s->accepted.load();
  if (native_reqs) *native_reqs = s->native_reqs.load();
  if (cb_frames) *cb_frames = s->cb_frames.load();
  if (handoffs) *handoffs = s->handoffs.load();
  if (live_conns) *live_conns = s->live_conns.load();
}

// ---------------------------------------------------------------------------
// per-connection API (token-addressed; any thread)
// ---------------------------------------------------------------------------

int tb_conn_respond(uint64_t token, const void* meta, size_t meta_len,
                    const void* payload, size_t payload_len, const void* att,
                    size_t att_len, uint32_t cid_lo, uint32_t cid_hi,
                    uint32_t flags, uint32_t error_code) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  tb_iobuf* out = tb_iobuf_create();
  pack_flat(out, meta, meta_len, payload, payload_len, att, att_len, cid_lo,
            cid_hi, flags | kFlagResponse, error_code);
  conn_queue_iobuf(c, out);
  tb_iobuf_destroy(out);
  conn_unref(c);
  return 0;
}

int tb_conn_write(uint64_t token, const tb_iobuf* data) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  conn_queue_iobuf(c, data);
  conn_unref(c);
  return 0;
}

int tb_conn_peer(uint64_t token, char* ip_out, size_t ip_cap) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  sockaddr_in addr{};
  socklen_t alen = sizeof addr;
  int port = -1;
  if (getpeername(c->fd, reinterpret_cast<sockaddr*>(&addr), &alen) == 0 &&
      addr.sin_family == AF_INET) {
    if (ip_out && ip_cap > 0) inet_ntop(AF_INET, &addr.sin_addr, ip_out, ip_cap);
    port = ntohs(addr.sin_port);
  }
  conn_unref(c);
  return port;
}

int tb_conn_close(uint64_t token) {
  NetConn* c = conn_resolve(token);
  if (c == nullptr) return -1;
  shutdown(c->fd, SHUT_RDWR);  // the loop thread reaps via EPOLLHUP
  conn_unref(c);
  return 0;
}

// ---------------------------------------------------------------------------
// client channel
// ---------------------------------------------------------------------------

namespace {

struct Pending {
  bool targeted;
  bool done = false;
  uint32_t err_code = 0;
  int fail = 0;   // -errno when the channel died under us
  std::string meta;
  tb_iobuf* body;  // targeted: caller's out buffer; any-mode: owned temp
};

}  // namespace

struct tb_channel {
  int fd = -1;
  std::mutex wmu;  // writers (pack + writev serialize)
  std::mutex rmu;  // reader election
  std::mutex pmu;  // pending table + done queue + cv
  std::condition_variable pcv;
  std::unordered_map<uint64_t, Pending*> pending;
  std::deque<std::pair<uint64_t, Pending*>> doneq;  // any-mode completions
  std::atomic<uint64_t> next_cid{1};
  tb_iobuf* rbuf = nullptr;
  tb_iobuf* pump_body = nullptr;  // reused per-response cut target (pump)
  std::atomic<int> err{0};  // sticky -errno
};

namespace {

void channel_fail(tb_channel* ch, int err) {
  ch->err.store(err, std::memory_order_release);
  std::lock_guard<std::mutex> g(ch->pmu);
  for (auto& kv : ch->pending) {
    if (!kv.second->done) {
      kv.second->done = true;
      kv.second->fail = err;
      if (!kv.second->targeted) ch->doneq.emplace_back(kv.first, kv.second);
    }
  }
  ch->pcv.notify_all();
}

// read whatever arrives within `slice_ms`, completing pendings.  Caller
// holds rmu.  Returns false when the channel failed.
bool pump_once(tb_channel* ch, int slice_ms) {
  pollfd pf{ch->fd, POLLIN, 0};
  int rc = poll(&pf, 1, slice_ms);
  if (rc < 0) {
    if (errno == EINTR) return true;
    channel_fail(ch, -errno);
    return false;
  }
  if (rc == 0) return true;
  size_t burst = tb_iobuf_read_burst();
  for (;;) {
    long n = tb_iobuf_append_from_fd(ch->rbuf, ch->fd, burst);
    if (n > 0) {
      if (static_cast<size_t>(n) < burst) break;
      continue;
    }
    if (n == -EAGAIN || n == -EWOULDBLOCK) break;
    if (n == -EINTR) continue;
    channel_fail(ch, n == 0 ? -EPIPE : static_cast<int>(n));
    return false;
  }
  for (;;) {
    tb_tbus_hdr hdr;
    int prc = tb_tbus_peek(ch->rbuf, &hdr);
    if (prc == 1) break;
    if (prc == -1 || hdr.meta_len > hdr.body_len ||
        hdr.body_len > (512u << 20)) {
      channel_fail(ch, -EPROTO);
      return false;
    }
    if (tb_iobuf_size(ch->rbuf) < kHeader + hdr.body_len) break;
    uint64_t cid = static_cast<uint64_t>(hdr.cid_lo) |
                   (static_cast<uint64_t>(hdr.cid_hi) << 32);
    std::string meta(hdr.meta_len, '\0');
    bool proto_err = false;
    {
      // completion runs under pmu so a timed-out caller can't free its
      // Pending (or its body iobuf) while the cut writes into it
      std::unique_lock<std::mutex> pl(ch->pmu);
      auto it = ch->pending.find(cid);
      Pending* p = it == ch->pending.end() ? nullptr : it->second;
      tb_iobuf* dst =
          (p != nullptr && p->targeted) ? p->body : tb_iobuf_create();
      int crc =
          tb_tbus_cut(ch->rbuf, &hdr, meta.empty() ? nullptr : &meta[0], dst);
      if (crc != 0) {
        if (p == nullptr || !p->targeted) tb_iobuf_destroy(dst);
        proto_err = true;
      } else if (p == nullptr) {
        tb_iobuf_destroy(dst);  // timed-out caller already left: drop
      } else {
        p->meta = std::move(meta);
        p->err_code = hdr.error_code;
        if (!p->targeted) {
          p->body = dst;
          ch->doneq.emplace_back(cid, p);
        }
        p->done = true;
        ch->pcv.notify_all();
      }
    }
    if (proto_err) {
      channel_fail(ch, -EPROTO);
      return false;
    }
  }
  return true;
}

// blocking full write of `frame` under wmu with a deadline
int write_frame(tb_channel* ch, tb_iobuf* frame, uint64_t deadline) {
  std::lock_guard<std::mutex> g(ch->wmu);
  while (tb_iobuf_size(frame) > 0) {
    long rc = tb_iobuf_cut_into_fd(frame, ch->fd, 4u << 20);
    if (rc > 0) continue;
    if (rc == -EINTR) continue;
    if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) {
      uint64_t now = now_ms();
      if (now >= deadline) return -ETIMEDOUT;
      pollfd pf{ch->fd, POLLOUT, 0};
      poll(&pf, 1, static_cast<int>(deadline - now));
      continue;
    }
    return static_cast<int>(rc);
  }
  return 0;
}

// pack with an explicit cid and write fully; 0 ok, -errno otherwise
int channel_send_cid(tb_channel* ch, uint64_t cid, const void* meta,
                     size_t meta_len, const void* payload, size_t payload_len,
                     const void* att, size_t att_len, uint32_t flags_extra,
                     uint64_t deadline) {
  tb_iobuf* frame = tb_iobuf_create();
  pack_flat(frame, meta, meta_len, payload, payload_len, att, att_len,
            static_cast<uint32_t>(cid), static_cast<uint32_t>(cid >> 32),
            flags_extra, 0);
  int rc = write_frame(ch, frame, deadline);
  tb_iobuf_destroy(frame);
  if (rc != 0 && rc != -ETIMEDOUT) channel_fail(ch, rc);
  return rc;
}

// shared wait-or-pump loop: wait until pred() under pmu, electing a reader
// to pump completions when nobody else is.  Returns false on deadline.
template <typename Pred>
bool wait_or_pump(tb_channel* ch, std::unique_lock<std::mutex>& pl,
                  uint64_t deadline, Pred pred) {
  while (!pred()) {
    if (ch->err.load(std::memory_order_acquire) != 0) return true;
    uint64_t now = now_ms();
    if (now >= deadline) return false;
    if (ch->rmu.try_lock()) {
      pl.unlock();
      int slice = static_cast<int>(std::min<uint64_t>(deadline - now, 50));
      pump_once(ch, slice);
      ch->rmu.unlock();
      pl.lock();
      ch->pcv.notify_all();
    } else {
      ch->pcv.wait_for(pl, std::chrono::milliseconds(10));
    }
  }
  return true;
}

}  // namespace

tb_channel* tb_channel_connect(const char* ip, int port, int timeout_ms,
                               int* err_out) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    if (err_out) *err_out = errno;
    return nullptr;
  }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    close(fd);
    if (err_out) *err_out = EINVAL;
    return nullptr;
  }
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr);
  if (rc != 0 && errno == EINPROGRESS) {
    pollfd pf{fd, POLLOUT, 0};
    rc = poll(&pf, 1, timeout_ms > 0 ? timeout_ms : 5000);
    if (rc == 1) {
      int soerr = 0;
      socklen_t slen = sizeof soerr;
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
      rc = soerr == 0 ? 0 : -1;
      errno = soerr;
    } else {
      rc = -1;
      errno = ETIMEDOUT;
    }
  }
  if (rc != 0) {
    if (err_out) *err_out = errno;
    close(fd);
    return nullptr;
  }
  set_nodelay(fd);
  set_nonblock(fd);
  tb_channel* ch = new tb_channel();
  ch->fd = fd;
  ch->rbuf = tb_iobuf_create();
  return ch;
}

long tb_channel_call(tb_channel* ch, const void* meta, size_t meta_len,
                     const void* payload, size_t payload_len, const void* att,
                     size_t att_len, uint32_t flags_extra, tb_iobuf* body_out,
                     void* meta_out, size_t meta_cap, uint32_t* meta_len_out,
                     uint32_t* err_code_out, int timeout_ms) {
  int sticky = ch->err.load(std::memory_order_acquire);
  if (sticky != 0) return sticky;
  uint64_t deadline = now_ms() + (timeout_ms > 0 ? timeout_ms : 60000);
  uint64_t cid = ch->next_cid.fetch_add(1, std::memory_order_relaxed);
  Pending p;
  p.targeted = true;
  p.body = body_out;
  {
    std::lock_guard<std::mutex> g(ch->pmu);
    ch->pending.emplace(cid, &p);
  }
  int rc = channel_send_cid(ch, cid, meta, meta_len, payload, payload_len, att,
                            att_len, flags_extra, deadline);
  if (rc != 0) {
    std::lock_guard<std::mutex> g(ch->pmu);
    ch->pending.erase(cid);
    return rc;
  }
  std::unique_lock<std::mutex> pl(ch->pmu);
  bool in_time = wait_or_pump(ch, pl, deadline, [&] { return p.done; });
  ch->pending.erase(cid);
  if (!in_time) return -ETIMEDOUT;
  if (!p.done) {  // channel failed before completion
    int e = ch->err.load(std::memory_order_acquire);
    return e != 0 ? e : -EPIPE;
  }
  int fail = p.fail;
  std::string meta_resp = std::move(p.meta);
  uint32_t ec = p.err_code;
  pl.unlock();
  if (fail != 0) return fail;
  if (meta_len_out)
    *meta_len_out = static_cast<uint32_t>(std::min(meta_resp.size(), meta_cap));
  if (meta_out && meta_cap > 0 && !meta_resp.empty())
    memcpy(meta_out, meta_resp.data(), std::min(meta_resp.size(), meta_cap));
  if (err_code_out) *err_code_out = ec;
  return static_cast<long>(tb_iobuf_size(body_out));
}

uint64_t tb_channel_send(tb_channel* ch, const void* meta, size_t meta_len,
                         const void* payload, size_t payload_len,
                         const void* att, size_t att_len, uint32_t flags_extra,
                         int* err_out) {
  int sticky = ch->err.load(std::memory_order_acquire);
  if (sticky != 0) {
    if (err_out) *err_out = -sticky;
    return 0;
  }
  uint64_t cid = ch->next_cid.fetch_add(1, std::memory_order_relaxed);
  Pending* p = new Pending();
  p->targeted = false;
  p->body = nullptr;
  {
    std::lock_guard<std::mutex> g(ch->pmu);
    ch->pending.emplace(cid, p);
  }
  int rc = channel_send_cid(ch, cid, meta, meta_len, payload, payload_len, att,
                            att_len, flags_extra, now_ms() + 60000);
  if (rc != 0) {
    std::lock_guard<std::mutex> g(ch->pmu);
    auto it = ch->pending.find(cid);
    if (it != ch->pending.end() && it->second == p && !p->done) {
      ch->pending.erase(it);
      delete p;
    }  // else channel_fail moved it to doneq: recv() frees it
    if (err_out) *err_out = -rc;
    return 0;
  }
  return cid;
}

long tb_channel_recv(tb_channel* ch, uint64_t* cid_out, tb_iobuf* body_out,
                     void* meta_out, size_t meta_cap, uint32_t* meta_len_out,
                     uint32_t* err_code_out, int timeout_ms) {
  uint64_t deadline = now_ms() + (timeout_ms > 0 ? timeout_ms : 60000);
  std::unique_lock<std::mutex> pl(ch->pmu);
  for (;;) {
    if (!ch->doneq.empty()) {
      auto [cid, p] = ch->doneq.front();
      ch->doneq.pop_front();
      ch->pending.erase(cid);
      pl.unlock();
      long n;
      if (p->fail != 0) {
        n = p->fail;
      } else {
        if (cid_out) *cid_out = cid;
        if (meta_len_out)
          *meta_len_out =
              static_cast<uint32_t>(std::min(p->meta.size(), meta_cap));
        if (meta_out && meta_cap > 0 && !p->meta.empty())
          memcpy(meta_out, p->meta.data(), std::min(p->meta.size(), meta_cap));
        if (err_code_out) *err_code_out = p->err_code;
        n = 0;
        if (p->body != nullptr) {
          n = static_cast<long>(tb_iobuf_size(p->body));
          tb_iobuf_append_iobuf(body_out, p->body);
        }
      }
      if (p->body != nullptr) tb_iobuf_destroy(p->body);
      delete p;
      return n;
    }
    int sticky = ch->err.load(std::memory_order_acquire);
    if (sticky != 0) {
      pl.unlock();
      return sticky;
    }
    if (!wait_or_pump(ch, pl, deadline, [&] { return !ch->doneq.empty(); })) {
      pl.unlock();
      return -ETIMEDOUT;
    }
  }
}

int tb_channel_error(const tb_channel* ch) {
  return ch->err.load(std::memory_order_acquire);
}

long tb_channel_pump(tb_channel* ch, const void* meta, size_t meta_len,
                     const void* payload, size_t payload_len, int n,
                     int inflight, int timeout_ms) {
  if (n <= 0) return -EINVAL;
  if (inflight < 1) inflight = 1;
  std::lock_guard<std::mutex> rg(ch->rmu);
  std::lock_guard<std::mutex> wg(ch->wmu);
  int sticky = ch->err.load(std::memory_order_acquire);
  if (sticky != 0) return sticky;
  uint64_t deadline = now_ms() + (timeout_ms > 0 ? timeout_ms : 60000);
  size_t burst = tb_iobuf_read_burst();
  tb_iobuf* frame = tb_iobuf_create();
  int sent = 0, done = 0, outstanding = 0;
  long result = 0;
  // every frame of the pump is identical except the correlation id: build
  // the wire bytes ONCE (header + meta + payload, meta crc precomputed)
  // and per request patch the 8 cid bytes + one append — no per-request
  // crc, header build, or multi-append
  std::vector<char> tmpl(32 + meta_len + payload_len);
  {
    uint32_t h[8];
    h[0] = kMagic;
    h[1] = static_cast<uint32_t>(meta_len + payload_len);
    h[2] = meta_len ? kFlagHasMeta : 0;
    h[3] = 0;
    h[4] = 0;
    h[5] = static_cast<uint32_t>(meta_len);
    h[6] = tb_crc32c(0, meta, meta_len);
    h[7] = 0;
    memcpy(tmpl.data(), h, sizeof h);
    if (meta_len) memcpy(tmpl.data() + 32, meta, meta_len);
    if (payload_len) memcpy(tmpl.data() + 32 + meta_len, payload, payload_len);
  }
  auto t0 = std::chrono::steady_clock::now();
  while (done < n && result == 0) {
    // fill the window: pack EVERY frame the window allows, then flush the
    // whole batch with as few writev calls as the kernel accepts (one
    // syscall per window refill, not per request)
    while (outstanding < inflight && sent < n) {
      uint64_t cid = ch->next_cid.fetch_add(1, std::memory_order_relaxed);
      uint32_t cid32[2] = {static_cast<uint32_t>(cid),
                           static_cast<uint32_t>(cid >> 32)};
      memcpy(tmpl.data() + 12, cid32, sizeof cid32);
      tb_iobuf_append(frame, tmpl.data(), tmpl.size());
      ++sent;
      ++outstanding;
    }
    while (tb_iobuf_size(frame) > 0) {
      long rc = tb_iobuf_cut_into_fd(frame, ch->fd, 4u << 20);
      if (rc > 0) continue;
      if (rc == -EINTR) continue;
      if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) break;  // kernel full
      result = rc;  // hard write error
      break;
    }
    if (result != 0) break;
    // drain completions (and finish any partial write while waiting)
    pollfd pf{ch->fd, static_cast<short>(
                          POLLIN | (tb_iobuf_size(frame) > 0 ? POLLOUT : 0)),
              0};
    uint64_t now = now_ms();
    if (now >= deadline) {
      result = -ETIMEDOUT;
      break;
    }
    int prc = poll(&pf, 1, static_cast<int>(std::min<uint64_t>(deadline - now, 100)));
    if (prc < 0 && errno != EINTR) {
      result = -errno;
      break;
    }
    if (pf.revents & POLLOUT) {
      while (tb_iobuf_size(frame) > 0) {
        long rc = tb_iobuf_cut_into_fd(frame, ch->fd, 4u << 20);
        if (rc > 0) continue;
        if (rc == -EINTR) continue;
        if (rc == 0 || rc == -EAGAIN || rc == -EWOULDBLOCK) break;
        result = rc;
        break;
      }
    }
    if (pf.revents & POLLIN) {
      for (;;) {
        long rd = tb_iobuf_append_from_fd(ch->rbuf, ch->fd, burst);
        if (rd > 0) {
          if (static_cast<size_t>(rd) < burst) break;
          continue;
        }
        if (rd == -EAGAIN || rd == -EWOULDBLOCK) break;
        if (rd == -EINTR) continue;
        result = rd == 0 ? -EPIPE : rd;
        break;
      }
      while (result == 0) {
        tb_tbus_hdr hdr;
        int prc2 = tb_tbus_peek(ch->rbuf, &hdr);
        if (prc2 == 1) break;
        if (prc2 == -1 || hdr.meta_len > hdr.body_len) {
          result = -EPROTO;
          break;
        }
        if (tb_iobuf_size(ch->rbuf) < kHeader + hdr.body_len) break;
        char mscratch[4096];
        if (hdr.meta_len > sizeof mscratch) {
          result = -EPROTO;
          break;
        }
        // one reusable body handle for the whole pump (clear per frame):
        // a create/destroy pair per response is pure overhead here
        if (ch->pump_body == nullptr) ch->pump_body = tb_iobuf_create();
        if (tb_tbus_cut(ch->rbuf, &hdr, hdr.meta_len ? mscratch : nullptr,
                        ch->pump_body) != 0)
          result = -EPROTO;
        tb_iobuf_clear(ch->pump_body);
        if (result == 0) {
          if (hdr.error_code != 0) result = -EREMOTEIO;
          ++done;
          --outstanding;
        }
      }
    }
  }
  tb_iobuf_destroy(frame);
  if (result != 0) return result;
  auto dt = std::chrono::duration_cast<std::chrono::nanoseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
  return static_cast<long>(dt / n);
}

void tb_channel_destroy(tb_channel* ch) {
  channel_fail(ch, -ECANCELED);
  if (ch->fd >= 0) close(ch->fd);
  std::unique_lock<std::mutex> pl(ch->pmu);
  for (auto& kv : ch->pending) {
    Pending* p = kv.second;
    if (!p->targeted) {
      if (p->body != nullptr) tb_iobuf_destroy(p->body);
      delete p;
    }
  }
  ch->pending.clear();
  ch->doneq.clear();
  pl.unlock();
  tb_iobuf_destroy(ch->rbuf);
  if (ch->pump_body != nullptr) tb_iobuf_destroy(ch->pump_body);
  delete ch;
}
